"""On-device input augmentation: PRNG-keyed random crop + horizontal flip.

The reference trains with bare `ToTensor()` (origin_main.py:89) — no
augmentation exists to port, but the ImageNet rung (ResNet-50, BASELINE
config 5) cannot train to real accuracy without crop/flip, so the data
layer needs the hook. TPU-first placement: augmentation runs INSIDE the
jitted train step, after the (device-resident) batch gather and the
uint8 -> float normalize — the host never touches pixels, the whole
epoch stays one dispatch under the resident driver (train/steps.py), and
XLA fuses the flip/crop gathers into the first conv's input read.

Determinism contract: the caller keys each step as
fold_in(fold_in(PRNGKey(seed), AUGMENT_TAG), global_step) — reproducible
for a given --seed, decorrelated from the dropout stream (different
fold-in tag), identical under the per-step, chunked-scan and resident
drivers at the same global step (which encodes epoch), and stable across
checkpoint resume (state.step restores).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# fold_in tag separating the augmentation stream from dropout (tag-free)
AUGMENT_TAG = 0x415547  # "AUG"


def random_crop_flip(
    images: jnp.ndarray,
    key: jax.Array,
    *,
    pad: int = 4,
    flip: bool = True,
) -> jnp.ndarray:
    """Pad-and-crop plus horizontal flip, per image, one fused program.

    images: (B, H, W, C) float (post-normalize). Zero-pads H/W by `pad`,
    takes a per-image random (H, W) window (offsets uniform in
    [0, 2*pad]), then mirrors each image left-right with probability 1/2.
    Static shapes throughout: the crop is a vmapped dynamic_slice, the
    flip a mask-select — no data-dependent shapes, scan/jit-safe.
    """
    b, h, w, c = images.shape
    kc, kf = jax.random.split(key)
    if pad > 0:
        padded = jnp.pad(
            images, ((0, 0), (pad, pad), (pad, pad), (0, 0))
        )
        off = jax.random.randint(kc, (b, 2), 0, 2 * pad + 1)

        def crop(img, o):
            return lax.dynamic_slice(img, (o[0], o[1], 0), (h, w, c))

        images = jax.vmap(crop)(padded, off)
    if flip:
        mirror = jax.random.bernoulli(kf, 0.5, (b,))
        images = jnp.where(
            mirror[:, None, None, None], images[:, :, ::-1, :], images
        )
    return images


def augment_rng(seed: int, step) -> jax.Array:
    """The per-step augmentation key (see module docstring contract)."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), AUGMENT_TAG), step
    )
