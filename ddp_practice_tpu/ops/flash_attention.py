"""Flash attention as a Pallas TPU kernel.

The reference consumes fused CUDA kernels through torch (cuDNN/cuBLAS —
SURVEY §2.2 "CUDA/cuDNN kernels"); the TPU-native analogue for the one op
XLA doesn't already fuse optimally at long sequence length is a hand-tiled
attention kernel. Forward pass (per q-block, per batch*head grid cell):

    for each k/v block:                       # fori_loop, VMEM-resident
        s   = q @ k^T * scale                 # MXU, fp32 accumulate
        m'  = max(m, rowmax(s))               # online softmax rescale
        acc = acc*exp(m-m') + exp(s-m') @ v   # MXU
    out = acc / l,   lse = m + log l

so the (seq x seq) score matrix never materializes in HBM — the FORWARD is
O(seq) memory instead of O(seq^2), one pass over K/V. Causal masking prunes
whole k-blocks above the diagonal (the fori upper bound shrinks per q-block).

Backward uses the saved logsumexp for a numerically exact dense recompute in
XLA (einsums on the MXU) — O(seq^2) activation memory; a tiled Pallas
backward (which the saved lse enables) is the planned follow-up, so today
the kernel's memory win applies to inference/eval and the forward half of
training. Runs compiled on TPU; `interpret=True` under the CPU backend so
the same tests cover it everywhere (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    *, sm_scale, block_k, causal, q_len_hint,
):
    block_q, head_dim = q_ref.shape
    seq_k = k_ref.shape[0]
    qi = pl.program_id(1)

    q = q_ref[:].astype(jnp.float32) * sm_scale

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    n_k = pl.cdiv(seq_k, block_k)
    # bottom-right-aligned causal (matches _attention's tril offset sk-sq):
    # query i attends keys <= i + (seq_k - seq_q)
    causal_offset = seq_k - q_len_hint if causal else 0
    if causal:
        # only k-blocks intersecting the allowed triangle of this q-block
        n_k = jnp.minimum(
            n_k, pl.cdiv((qi + 1) * block_q + causal_offset, block_k)
        )

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l_safe))[:, None]  # (block_q, 1) lane-padded


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: (bh, seq, d). Returns (out, lse)."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"flash attention needs seq divisible by block sizes: "
            f"q {seq_q}%{block_q}, k {seq_k}%{block_k}"
        )
    if causal and seq_q > seq_k:
        raise ValueError(
            f"causal flash attention needs seq_q <= seq_k (bottom-right "
            f"alignment); got seq_q={seq_q}, seq_k={seq_k} — early query "
            f"rows would attend to nothing"
        )
    sm_scale = 1.0 / (d ** 0.5)
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
        q_len_hint=seq_q,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() == "cpu"
    out, _ = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() == "cpu"
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    """Exact dense recompute using the saved logsumexp (XLA einsums)."""
    q, k, v, out, lse = res
    in_dtype = q.dtype
    d = q.shape[-1]
    sm_scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])                      # exact probs
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # rowsum(do*o)
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(in_dtype), dk.astype(in_dtype), dv.astype(in_dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,  # (batch, seq, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Fused multi-head attention; layout-matches ops.attention._attention."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    def fold(x, s):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, x.shape[-1])

    out = _flash(fold(q, sq), fold(k, sk), fold(v, sk), causal, block_q, block_k)
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
