"""Flash attention as Pallas TPU kernels — forward AND tiled backward.

The reference consumes fused CUDA kernels through torch (cuDNN/cuBLAS —
SURVEY §2.2 "CUDA/cuDNN kernels"); the TPU-native analogue for the one op
XLA doesn't already fuse optimally at long sequence length is a hand-tiled
attention kernel. Forward pass (per q-block, per batch*head grid cell):

    for each k/v block:                       # fori_loop, VMEM-resident
        s   = q @ k^T * scale                 # MXU, fp32 accumulate
        m'  = max(m, rowmax(s))               # online softmax rescale
        acc = acc*exp(m-m') + exp(s-m') @ v   # MXU
    out = acc / l,   lse = m + log l

so the (seq x seq) score matrix never materializes in HBM — O(seq) memory,
one pass over K/V. Causal masking prunes whole k-blocks above the diagonal.

Backward is tiled the same way (FlashAttention-2 scheme), recomputing
p = exp(s - lse) blockwise from the saved logsumexp:

    delta = rowsum(do * o)                    # XLA, cheap
    dKdV kernel (grid over k-blocks): for each q-block:
        p = exp(q@k^T*scale - lse);  dv += p^T @ do
        ds = p * (do @ v^T - delta); dk += ds^T @ (q*scale)
    dQ kernel (grid over q-blocks): for each k-block:
        dq += (ds @ k) * scale

so training memory is O(seq) end to end. `flash_attention_with_lse`
additionally exposes lse as a differentiable output — the lse cotangent
folds into delta (d lse/d s = p, so ds gains p*g_lse, i.e. delta -= g_lse)
— which is what lets ring attention use this kernel as its per-block local
attention and merge normalized partials across ring steps
(parallel/ring.py).

Runs compiled on TPU; `interpret=True` under the CPU backend so the same
tests cover it everywhere (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    *, sm_scale, block_k, causal, q_len_hint,
):
    block_q, head_dim = q_ref.shape
    seq_k = k_ref.shape[0]
    qi = pl.program_id(1)

    q = q_ref[:].astype(jnp.float32) * sm_scale

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    n_k = pl.cdiv(seq_k, block_k)
    # bottom-right-aligned causal (matches _attention's tril offset sk-sq):
    # query i attends keys <= i + (seq_k - seq_q)
    causal_offset = seq_k - q_len_hint if causal else 0
    if causal:
        # only k-blocks intersecting the allowed triangle of this q-block
        n_k = jnp.minimum(
            n_k, pl.cdiv((qi + 1) * block_q + causal_offset, block_k)
        )

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l_safe))[:, None]  # (block_q, 1) lane-padded


def _check_blocks(seq_q, seq_k, block_q, block_k, causal):
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"flash attention needs seq divisible by block sizes: "
            f"q {seq_q}%{block_q}, k {seq_k}%{block_k}"
        )
    if causal and seq_q > seq_k:
        raise ValueError(
            f"causal flash attention needs seq_q <= seq_k (bottom-right "
            f"alignment); got seq_q={seq_q}, seq_k={seq_k} — early query "
            f"rows would attend to nothing"
        )
    return block_q, block_k


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: (bh, seq, d). Returns (out, lse)."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
        q_len_hint=seq_q,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref,
    *, sm_scale, block_q, causal, q_len_hint,
):
    """Grid cell: one k/v block; loops over q blocks (FlashAttention-2)."""
    block_k, head_dim = k_ref.shape
    seq_q = q_ref.shape[0]
    ki = pl.program_id(1)

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    dk0 = jnp.zeros((block_k, head_dim), jnp.float32)
    dv0 = jnp.zeros((block_k, head_dim), jnp.float32)

    n_q = pl.cdiv(seq_q, block_q)
    causal_offset = (k_ref.shape[0] * pl.num_programs(1)) - q_len_hint \
        if causal else 0
    q_start = 0
    if causal:
        # first q-block whose last row can see this k-block:
        # q_pos + offset >= k_pos  =>  q_pos >= ki*block_k - offset
        q_start = jnp.maximum(0, (ki * block_k - causal_offset) // block_q)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32) * sm_scale
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :]  # (block_q, 1) fp32
        delta = delta_ref[pl.ds(i * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # exact probs (block)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(q_start, n_q, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref,
    *, sm_scale, block_k, causal, q_len_hint,
):
    """Grid cell: one q block; loops over k blocks."""
    block_q, head_dim = q_ref.shape
    seq_k = k_ref.shape[0]
    qi = pl.program_id(1)

    q = q_ref[:].astype(jnp.float32) * sm_scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]           # (block_q, 1) fp32
    delta = delta_ref[:]

    dq0 = jnp.zeros((block_q, head_dim), jnp.float32)
    n_k = pl.cdiv(seq_k, block_k)
    causal_offset = seq_k - q_len_hint if causal else 0
    if causal:
        n_k = jnp.minimum(
            n_k, pl.cdiv((qi + 1) * block_q + causal_offset, block_k)
        )

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_k, body, dq0)
    dq_ref[:] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd(q, k, v, do, lse, delta, *, causal, block_q, block_k,
               interpret):
    """Tiled dq/dk/dv. delta = rowsum(do*o) - g_lse, fp32 (bh, seq_q)."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    lse3 = lse[..., None].astype(jnp.float32)
    delta3 = delta[..., None].astype(jnp.float32)

    dkdv = functools.partial(
        _dkdv_kernel, sm_scale=sm_scale, block_q=block_q, causal=causal,
        q_len_hint=seq_q,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, seq_q, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, seq_q, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)

    dqk = functools.partial(
        _dq_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
        q_len_hint=seq_q,
    )
    dq = pl.pallas_call(
        dqk,
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)
    return dq, dk, dv


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


# --------------------------------------------------------------------- #
# Differentiable entry points.
# _flash_lse returns (out, lse), both differentiable; the lse cotangent
# folds into delta (see module docstring). flash_attention drops lse.
# --------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return out, lse


def _flash_lse_vjp_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    g_out = g_out.astype(q.dtype)
    delta = jnp.sum(
        g_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None and not isinstance(
        g_lse, jax.custom_derivatives.SymbolicZero
    ):
        delta = delta - g_lse.astype(jnp.float32)
    dq, dk, dv = _flash_bwd(
        q, k, v, g_out, lse, delta,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(
    q: jnp.ndarray,  # (batch_heads, seq, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
):
    """Fused attention over folded (b*h, s, d) layout, returning (out, lse).

    lse is a differentiable output — the building block ring attention uses
    to merge per-ring-step partials (parallel/ring.py)."""
    return _flash_lse(q, k, v, causal, block_q, block_k)


def flash_attention(
    q: jnp.ndarray,  # (batch, seq, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Fused multi-head attention; layout-matches ops.attention._attention."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    def fold(x, s):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, x.shape[-1])

    out, _ = _flash_lse(
        fold(q, sq), fold(k, sk), fold(v, sk), causal, block_q, block_k
    )
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
