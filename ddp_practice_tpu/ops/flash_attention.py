"""Flash attention as Pallas TPU kernels — forward AND tiled backward.

The reference consumes fused CUDA kernels through torch (cuDNN/cuBLAS —
SURVEY §2.2 "CUDA/cuDNN kernels"); the TPU-native analogue for the one op
XLA doesn't already fuse optimally at long sequence length is a hand-tiled
attention kernel. All three kernels are STREAMING: a 3D grid
(batch*heads, outer-block, inner-block) whose innermost dimension sweeps
the contracted sequence axis while per-block state lives in VMEM scratch
— so only one q tile and one k/v tile are VMEM-resident at any moment and
sequence length is bounded by HBM, not VMEM. Forward, per (q-block,
k-block) grid step:

    @when(kj == 0):   m, l, acc := -inf, 0, 0  # scratch init
    s   = q @ k^T * scale                      # MXU, fp32 accumulate
    m'  = max(m, rowmax(s))                    # online softmax rescale
    acc = acc*exp(m-m') + exp(s-m') @ v        # MXU
    @when(kj == last): out = acc / l, lse = m + log l

so the (seq x seq) score matrix never materializes in HBM — O(seq) memory,
one pass over K/V. Causal masking skips whole k-blocks above the diagonal
(@when(visible) gates the FLOPs).

Backward is tiled the same way (FlashAttention-2 scheme), recomputing
p = exp(s - lse) blockwise from the saved logsumexp:

    delta = rowsum(do * o)                    # XLA, cheap
    dKdV kernel (grid bh x k-blocks x q-blocks, q innermost):
        p = exp(q@k^T*scale - lse);  dv += p^T @ do     # scratch accum
        ds = p * (do @ v^T - delta); dk += ds^T @ (q*scale)
    dQ kernel (grid bh x q-blocks x k-blocks, k innermost):
        dq += (ds @ k) * scale                          # scratch accum

so training memory is O(seq) end to end. `flash_attention_with_lse`
additionally exposes lse as a differentiable output — the lse cotangent
folds into delta (d lse/d s = p, so ds gains p*g_lse, i.e. delta -= g_lse)
— which is what lets ring attention use this kernel as its per-block local
attention and merge normalized partials across ring steps
(parallel/ring.py).

Runs compiled on TPU; `interpret=True` under the CPU backend so the same
tests cover it everywhere (tests/conftest.py).

Hardware validation (TPU v5e, 2026-07-30, compiled — not interpret):
fwd+bwd vs a Precision.HIGHEST dense reference at (4, 1024, 8, 64),
causal and non-causal: max relative grad error 3-7e-3 — MXU default-
precision (bf16-pass) noise, the same regime XLA's own dense attention
computes in at default precision.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    """Bottom-right-aligned causal mask for one (q-block, k-block) tile:
    query i attends keys <= i + offset, offset = seq_k - seq_q (matches
    _attention's tril)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos + offset >= k_pos, s, _NEG_INF)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, q-block, k-block): k innermost, so only one
    (block_q, d) + one (block_k, d) tile live in VMEM at a time — sequence
    length is unbounded by VMEM. Online-softmax state (m, l, acc) persists
    in scratch across the k sweep; the output block writes on the last k
    step (Pallas copies revisited out-blocks out once, at the end)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # a k-block fully above the diagonal contributes nothing: skip its FLOPs
    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = (l_prev * corr + jnp.sum(p, axis=-1))[:, None]
        acc_scr[:] = acc_scr[:] * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new[:, None]

    @pl.when(kj == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[:] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]


def _fit_block(seq, block):
    """Largest block <= the requested size that divides seq (blocks are
    upper bounds, not contracts: seq 1536 with default block_k 1024 fits
    down to 512 instead of erroring; seq <= block clamps to seq)."""
    block = min(block, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


def _check_blocks(seq_q, seq_k, block_q, block_k, causal):
    block_q = _fit_block(seq_q, block_q)
    block_k = _fit_block(seq_k, block_k)
    if causal and seq_q > seq_k:
        raise ValueError(
            f"causal flash attention needs seq_q <= seq_k (bottom-right "
            f"alignment); got seq_q={seq_q}, seq_k={seq_k} — early query "
            f"rows would attend to nothing"
        )
    return block_q, block_k


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: (bh, seq, d). Returns (out, lse)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    grid = (bh, seq_q // block_q, seq_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, k-block, q-block): q innermost; dk/dv
    accumulate in scratch across the q sweep (FlashAttention-2), writing
    the output block on the last q step. Only one q tile + one k/v tile
    are VMEM-resident — seq is unbounded by VMEM."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (ki * block_k)
        if causal else (qi >= 0)
    )

    @pl.when(visible)
    def _compute():
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        q = q_ref[:].astype(jnp.float32) * sm_scale
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:]      # (block_q, 1) fp32
        delta = delta_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse)  # exact probs from the saved logsumexp
        dv_scr[:] = dv_scr[:] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[:] = dk_scr[:] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref, dq_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, q-block, k-block): k innermost; dq
    accumulates in scratch across the k sweep."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:]
        delta = delta_ref[:]
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[:] = dq_scr[:] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[:] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd(q, k, v, do, lse, delta, *, causal, block_q, block_k,
               interpret):
    """Tiled dq/dk/dv. delta = rowsum(do*o) - g_lse, fp32 (bh, seq_q)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    lse3 = lse[..., None].astype(jnp.float32)
    delta3 = delta[..., None].astype(jnp.float32)

    dkdv = functools.partial(
        _dkdv_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, seq_k // block_k, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)

    dqk = functools.partial(
        _dq_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    dq = pl.pallas_call(
        dqk,
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)
    return dq, dk, dv


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


# --------------------------------------------------------------------- #
# Differentiable entry points.
# _flash_lse returns (out, lse), both differentiable; the lse cotangent
# folds into delta (see module docstring). flash_attention drops lse.
# --------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return out, lse


def _flash_lse_vjp_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    g_out = g_out.astype(q.dtype)
    delta = jnp.sum(
        g_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None and not isinstance(
        g_lse, jax.custom_derivatives.SymbolicZero
    ):
        delta = delta - g_lse.astype(jnp.float32)
    dq, dk, dv = _flash_bwd(
        q, k, v, g_out, lse, delta,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(
    q: jnp.ndarray,  # (batch_heads, seq, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
):
    """Fused attention over folded (b*h, s, d) layout, returning (out, lse).

    lse is a differentiable output — the building block ring attention uses
    to merge per-ring-step partials (parallel/ring.py)."""
    return _flash_lse(q, k, v, causal, block_q, block_k)


def flash_attention(
    q: jnp.ndarray,  # (batch, seq, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
) -> jnp.ndarray:
    """Fused multi-head attention; layout-matches ops.attention._attention.

    Default blocks (512, 1024) are the measured sweet spot on TPU v5e for
    lm_base shapes (head_dim 64): lm bench 34.1% MFU at seq 2048 and
    27.9% at seq 8192, vs 29%/20% at (256, 512) — kernel sweep
    2026-07-30, BENCHMARKS.md. Blocks clamp to the sequence length, so
    short-seq callers (ViT at s=64) are unaffected."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    def fold(x, s):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, x.shape[-1])

    out, _ = _flash_lse(
        fold(q, sq), fold(k, sk), fold(v, sk), causal, block_q, block_k
    )
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
