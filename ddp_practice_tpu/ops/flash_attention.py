"""Flash attention as Pallas TPU kernels — forward AND tiled backward.

The reference consumes fused CUDA kernels through torch (cuDNN/cuBLAS —
SURVEY §2.2 "CUDA/cuDNN kernels"); the TPU-native analogue for the one op
XLA doesn't already fuse optimally at long sequence length is a hand-tiled
attention kernel. All three kernels are STREAMING: a 3D grid
(batch*heads, outer-block, inner-block) whose innermost dimension sweeps
the contracted sequence axis while per-block state lives in VMEM scratch
— so only one q tile and one k/v tile are VMEM-resident at any moment and
sequence length is bounded by HBM, not VMEM. Forward, per (q-block,
k-block) grid step:

    @when(kj == 0):   m, l, acc := -inf, 0, 0  # scratch init
    s   = (q*scale) @ k^T                      # MXU, fp32 accumulate
    m'  = max(m, rowmax(s))                    # online softmax rescale
    acc = acc*(l*corr/l') + exp(s-m') @ v / l' # MXU; acc stays normalized
    @when(kj == last): out = acc, lse = m + log l

so the (seq x seq) score matrix never materializes in HBM — O(seq) memory,
one pass over K/V. Causal masking skips whole k-blocks above the diagonal
(@when(visible) gates the FLOPs).

Performance structure (the round-4 restructure; measured on TPU v5e —
see BENCHMARKS.md kernel table):
  * softmax state (m, l) is kept LANE-REPLICATED at (block_q, 128) and
    widened to block_k by lane-tiling — never a width-1 cross-lane
    broadcast over the (block_q, block_k) tile, which dominated VPU time
    in the round-3 kernel;
  * the accumulator is renormalized every step, so the epilogue is a bare
    cast (no wide divide), and all broadcasts against acc slice the
    replicated 128-lane state down to head_dim;
  * all contractions are `lax.dot_general` with explicit dimension
    numbers — k^T / p^T / ds^T are never materialized;
  * sm_scale is folded into the q tile at load ((block_q, d) mul — for
    d=64 the scale 1/8 is exact in bf16) so no (block_q, block_k) scale
    pass runs;
  * p / ds are cast to bf16 before their MXU consumers (FlashAttention-2
    staging); softmax statistics stay fp32.
With head_dim 64 the MXU contraction/output width caps useful utilization
at 50% of peak; the restructured forward reaches ~49% of bf16 peak on the
executed-dot basis at lm_base shapes — at the structural ceiling.

Backward is tiled the same way (FlashAttention-2 scheme), recomputing
p = exp(s - lse) blockwise from the saved logsumexp:

    delta = rowsum(do * o)                    # XLA, cheap
    dKdV kernel (grid bh x k-blocks x q-blocks, q innermost):
        p = exp(qs@k^T - lse);  dv += p^T @ do          # scratch accum
        ds = p * (do @ v^T - delta); dk += ds^T @ qs
    dQ kernel (grid bh x q-blocks x k-blocks, k innermost):
        dq += (ds @ k) * scale                          # scratch accum

so training memory is O(seq) end to end. `flash_attention_with_lse`
additionally exposes lse as a differentiable output — the lse cotangent
folds into delta (d lse/d s = p, so ds gains p*g_lse, i.e. delta -= g_lse)
— which is what lets ring attention use this kernel as its per-block local
attention and merge normalized partials across ring steps
(parallel/ring.py).

Runs compiled on TPU; `interpret=True` under the CPU backend so the same
tests cover it everywhere (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from ddp_practice_tpu.ops.pallas_compat import tpu_compiler_params

_NEG_INF = -1e30
_LANES = 128

# dot_general dimension numbers: contract the LAST dim of both operands
# (x @ y^T without materializing the transpose) and the FIRST dim of both
# (x^T @ y likewise).
_TRANS_B = (((1,), (1,)), ((), ()))
_TRANS_A = (((0,), (0,)), ((), ()))


def _dot_tb(x, y):
    return lax.dot_general(x, y, _TRANS_B, preferred_element_type=jnp.float32)


def _dot_ta(x, y):
    return lax.dot_general(x, y, _TRANS_A, preferred_element_type=jnp.float32)


def _widen(x128, w):
    """Widen lane-replicated (rows, 128) state to (rows, w) without a
    width-1 cross-lane broadcast: slice when w <= 128, lane-tile when w is
    a multiple of 128, fall back to a plain broadcast otherwise (rare,
    non-tiled shapes)."""
    if w <= _LANES:
        return x128[:, :w]
    if w % _LANES == 0:
        return jnp.tile(x128, (1, w // _LANES))
    return jnp.broadcast_to(x128[:, :1], (x128.shape[0], w))


def _softmax_accumulate(s, v_tile, m_prev, l_prev, acc_prev, *,
                        vs_row=None):
    """One online-softmax accumulation step, shared by every forward
    kernel (folded, packed, decode): fold the fp32 score tile `s`
    (rows, block_k) and its value tile into lane-replicated (rows, 128)
    running max/denominator state and a NORMALIZED accumulator
    (rows, d). Returns (m_next, l_next, acc_next).

    `vs_row` (rows, block_k) handles an INT8 value tile with
    per-position dequant scales: p @ diag(vs) @ V == (p * vs_row) @ V,
    so the scale folds into the probability row BEFORE the dot and the
    MXU still consumes the raw tile. The softmax DENOMINATOR stays
    unscaled — vs dequantizes values, it is not probability mass."""
    block_k = s.shape[-1]
    d = acc_prev.shape[-1]
    m_next = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    p = jnp.exp(s - _widen(m_next, block_k))
    alpha = jnp.exp(m_prev - m_next)
    l_corr = alpha * l_prev
    l_next = l_corr + jnp.sum(p, axis=1)[:, None]
    l_inv = jnp.where(l_next == 0.0, 1.0, 1.0 / l_next)
    pv = lax.dot_general(
        (p if vs_row is None else p * vs_row).astype(v_tile.dtype),
        v_tile, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_next = acc_prev * _widen(l_corr * l_inv, d) + pv * _widen(l_inv, d)
    return m_next, l_next, acc_next


def _causal_penalty(qi, kj, block_q, block_k, offset):
    """Additive mask for one (q-block, k-block) tile: 0 where query i may
    attend key j (j <= i + offset, offset = seq_k - seq_q), -inf-like
    otherwise. Added to s (cheaper than select on Mosaic)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos + offset >= k_pos, 0.0, _NEG_INF)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, q-block, k-block): k innermost, so only one
    (block_q, d) + one (block_k, d) tile live in VMEM at a time — sequence
    length is unbounded by VMEM. Online-softmax state (m, l) persists
    lane-replicated at (block_q, 128) in scratch across the k sweep; acc
    is kept normalized every step so the final write is a cast."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0
    d = v_ref.shape[-1]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # a k-block fully above the diagonal contributes nothing: skip its FLOPs
    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = (q_ref[:] * sm_scale).astype(q_ref.dtype)  # (bq, d), cheap
        s = _dot_tb(q, k_ref[:])                       # (bq, bk) fp32
        if causal:
            s = s + _causal_penalty(qi, kj, block_q, block_k, offset)
        m_scr[:], l_scr[:], acc_scr[:] = _softmax_accumulate(
            s, v_ref[:], m_scr[:], l_scr[:], acc_scr[:]
        )

    @pl.when(kj == n_k - 1)
    def _finalize():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)
        l_col = l_scr[:, :1]
        l_safe = jnp.maximum(l_col, 1e-30)
        lse_ref[:] = m_scr[:, :1] + jnp.log(l_safe)


def _fit_block(seq, block):
    """Largest block <= the requested size that divides seq (blocks are
    upper bounds, not contracts: seq 1536 with default block_k 1024 fits
    down to 512 instead of erroring; seq <= block clamps to seq)."""
    block = min(block, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


def _check_blocks(seq_q, seq_k, block_q, block_k, causal):
    block_q = _fit_block(seq_q, block_q)
    block_k = _fit_block(seq_k, block_k)
    if causal and seq_q > seq_k:
        raise ValueError(
            f"causal flash attention needs seq_q <= seq_k (bottom-right "
            f"alignment); got seq_q={seq_q}, seq_k={seq_k} — early query "
            f"rows would attend to nothing"
        )
    return block_q, block_k


def _block_visible(block_q, block_k, offset):
    """Predicate: does causal q-block i see any of k-block j?"""
    return lambda i, j: (i * block_q + block_q - 1 + offset) >= (j * block_k)


def _redirect(causal, vis, i, j, idx):
    """Prefetch-redirect for swept block indices: a block belonging to a
    cell the kernel will skip (fully above the diagonal) redirects its
    DMA to block 0 instead of fetching data that `@pl.when(visible)`
    discards (the bundled jax TPU kernel's prefetch trick). All six
    sweep index maps below (folded + packed, kv- and q-swept) are built
    from this one predicate+select so the visibility condition lives in
    exactly one place."""
    return lax.select(vis(i, j), idx, 0) if causal else idx


def _kv_index_map(causal, block_q, block_k, offset):
    """kv-block index map for k-innermost folded sweeps."""
    vis = _block_visible(block_q, block_k, offset)
    return lambda b, i, j: (b, _redirect(causal, vis, i, j, j), 0)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: (bh, seq, d). Returns (out, lse)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    grid = (bh, seq_q // block_q, seq_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    kv_map = _kv_index_map(causal, block_q, block_k,
                           seq_k - seq_q if causal else 0)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), kv_map),
            pl.BlockSpec((None, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_probs(q_scaled, k_ref, lse_ref, qi, kj, block_q, block_k, causal,
               offset):
    """Recompute the (block_q, block_k) probability tile from the saved
    logsumexp: p = exp(qs@k^T - lse). lse arrives as a (block_q, 1) column;
    it is broadcast once to the 128-lane replicated form and lane-widened
    from there (never a width-1 broadcast at block_k width)."""
    s = _dot_tb(q_scaled, k_ref[:])
    if causal:
        s = s + _causal_penalty(qi, kj, block_q, block_k, offset)
    lse128 = jnp.broadcast_to(lse_ref[:], (block_q, _LANES))
    return jnp.exp(s - _widen(lse128, block_k))


def _dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, k-block, q-block): q innermost; dk/dv
    accumulate in scratch across the q sweep (FlashAttention-2), writing
    the output block on the last q step. Only one q tile + one k/v tile
    are VMEM-resident — seq is unbounded by VMEM."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (ki * block_k)
        if causal else (qi >= 0)
    )

    @pl.when(visible)
    def _compute():
        qs = (q_ref[:] * sm_scale).astype(q_ref.dtype)
        do = do_ref[:]
        p = _bwd_probs(qs, k_ref, lse_ref, qi, ki, block_q, block_k,
                       causal, offset)
        p_lo = p.astype(do.dtype)
        dv_scr[:] = dv_scr[:] + _dot_ta(p_lo, do)       # p^T @ do
        dp = _dot_tb(do, v_ref[:])                      # do @ v^T
        delta128 = jnp.broadcast_to(delta_ref[:], (block_q, _LANES))
        ds = p * (dp - _widen(delta128, block_k))
        dk_scr[:] = dk_scr[:] + _dot_ta(ds.astype(qs.dtype), qs)  # ds^T @ qs

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref, dq_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k,
):
    """Streaming grid cell (bh, q-block, k-block): k innermost; dq
    accumulates in scratch across the k sweep."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        qs = (q_ref[:] * sm_scale).astype(q_ref.dtype)
        do = do_ref[:]
        p = _bwd_probs(qs, k_ref, lse_ref, qi, kj, block_q, block_k,
                       causal, offset)
        dp = _dot_tb(do, v_ref[:])
        delta128 = jnp.broadcast_to(delta_ref[:], (block_q, _LANES))
        ds = (p * (dp - _widen(delta128, block_k))).astype(q_ref.dtype)
        dq_scr[:] = dq_scr[:] + lax.dot_general(        # ds @ k
            ds, k_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[:] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd(q, k, v, do, lse, delta, *, causal, block_q, block_k,
               interpret):
    """Tiled dq/dk/dv. delta = rowsum(do*o) - g_lse, fp32 (bh, seq_q)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    lse3 = lse[..., None].astype(jnp.float32)
    delta3 = delta[..., None].astype(jnp.float32)

    offset = seq_k - seq_q if causal else 0
    vis = _block_visible(block_q, block_k, offset)

    def qo_map(b, j, i):
        return (b, _redirect(causal, vis, i, j, i), 0)

    dkdv = functools.partial(
        _dkdv_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, seq_k // block_k, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), qo_map),
            pl.BlockSpec((None, block_q, d), qo_map),
            pl.BlockSpec((None, block_q, 1), qo_map),
            pl.BlockSpec((None, block_q, 1), qo_map),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)

    dqk = functools.partial(
        _dq_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    kv_map = _kv_index_map(causal, block_q, block_k, offset)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), kv_map),
            pl.BlockSpec((None, block_k, d), kv_map),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, do, lse3, delta3, k, v)
    return dq, dk, dv


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


# --------------------------------------------------------------------- #
# Packed-layout kernels: attention directly on the flat (b, s, h*d)
# activations the QKV projection produces.
#
# The folded path above transposes (b, s, h, d) -> (b*h, s, d) around
# every kernel call; at lm_base shapes those transposes are ~5% of the
# whole train step ("data formatting" in the xprof composition —
# BENCHMARKS.md). Mosaic cannot squeeze a size-h dim out of a 4D block,
# but it CAN take a 128-wide column block out of the flat h*d dim — so
# for d <= 128 we pack 128//d heads per grid cell: the q/k/v tiles are
# (block, 128) contiguous slices of the UNTRANSPOSED activations, and the
# kernel walks the packed heads with 64-aligned column slices (python-
# unrolled). Head count h must divide into whole packs; anything else
# falls back to the folded path. Zero layout ops at the model boundary.
# --------------------------------------------------------------------- #


def _heads_per_pack(h: int, d: int):
    """Packing arity for head_dim d: how many heads share one 128-lane
    tile. None = shapes don't pack (fall back to the folded path).
    d < 64 is excluded even when it divides 128: the in-kernel head walk
    slices columns at h*d offsets, and Mosaic only supports 64-aligned
    column slices (tpu-env-gotchas)."""
    if d >= _LANES:
        return 1 if d % _LANES == 0 else None
    if d < 64 or _LANES % d:
        return None
    hpc = _LANES // d
    return hpc if h % hpc == 0 else None


def _fwd_kernel_packed(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k, hpc, d,
):
    """Packed grid cell (b, head-pack, q-block, k-block): identical math
    to _fwd_kernel, repeated over the hpc heads living in this 128-wide
    column pack. Per-head state is (hpc, block_q, 128) scratch; the
    accumulator shares one (block_q, hpc*d) buffer whose column blocks
    belong to the packed heads."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    # NOTE: a diagonal/interior split (interior cells skipping the iota/
    # where penalty) measured NEUTRAL on v5e (1.33 vs 1.30 ms at lm_base
    # shapes — the duplicated body costs what the skipped pass saves), so
    # the penalty runs on every visited cell, like the bundled jax kernel.
    @pl.when(visible)
    def _compute():
        penalty = (
            _causal_penalty(qi, kj, block_q, block_k, offset)
            if causal else None
        )
        for hh in range(hpc):
            lo, hi = hh * d, (hh + 1) * d
            q = (q_ref[:, lo:hi] * sm_scale).astype(q_ref.dtype)
            s = _dot_tb(q, k_ref[:, lo:hi])
            if causal:
                s = s + penalty
            m_scr[hh], l_scr[hh], acc_scr[:, lo:hi] = _softmax_accumulate(
                s, v_ref[:, lo:hi], m_scr[hh], l_scr[hh], acc_scr[:, lo:hi]
            )

    @pl.when(kj == n_k - 1)
    def _finalize():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)
        for hh in range(hpc):
            l_safe = jnp.maximum(l_scr[hh][:, :1], 1e-30)
            lse_ref[:, hh:hh + 1] = m_scr[hh][:, :1] + jnp.log(l_safe)


def _flash_fwd_packed(qf, kf, vf, *, n_heads, causal, block_q, block_k,
                      interpret, fused_qkv=False):
    """qf/kf/vf: flat (b, s, h*d). Returns (out_flat, lse_packed) where
    lse_packed is (b, n_packs, seq_q, hpc) fp32.

    fused_qkv=True: qf/kf/vf are all the SAME (b, s, 3*h*d) array — the
    raw QKV-projection output, columns [q heads | k heads | v heads].
    The three in_specs window it at column-block offsets (0, n_packs,
    2*n_packs), so no slice/relayout ever materializes q, k, v (the
    sliced path cost ~4 ms/step of pure data formatting at lm_base
    shapes — round-4 profile)."""
    from jax.experimental.pallas import tpu as pltpu

    b, seq_q, hd = qf.shape
    if fused_qkv:
        hd //= 3
    seq_k = kf.shape[1]
    d = hd // n_heads
    hpc = _heads_per_pack(n_heads, d)
    w = hpc * d
    n_packs = n_heads // hpc
    koff = n_packs if fused_qkv else 0
    voff = 2 * n_packs if fused_qkv else 0
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    offset = seq_k - seq_q if causal else 0
    vis = _block_visible(block_q, block_k, offset)

    def k_map(b_, g, i, j):
        return (b_, _redirect(causal, vis, i, j, j), g + koff)

    def v_map(b_, g, i, j):
        return (b_, _redirect(causal, vis, i, j, j), g + voff)

    kernel = functools.partial(
        _fwd_kernel_packed, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, causal=causal, seq_q=seq_q, seq_k=seq_k,
        hpc=hpc, d=d,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, n_packs, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, w), lambda b_, g, i, j: (b_, i, g)),
            pl.BlockSpec((None, block_k, w), k_map),
            pl.BlockSpec((None, block_k, w), v_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, w), lambda b_, g, i, j: (b_, i, g)),
            pl.BlockSpec((None, None, block_q, hpc),
                         lambda b_, g, i, j: (b_, g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, seq_q, hd), qf.dtype),
            jax.ShapeDtypeStruct((b, n_packs, seq_q, hpc), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((hpc, block_q, _LANES), jnp.float32),
            pltpu.VMEM((hpc, block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, w), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf)
    return out, lse


def _dkdv_kernel_packed(
    q_ref, do_ref, out_ref, lse_ref, k_ref, v_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k, hpc, d,
):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)
    offset = seq_k - seq_q if causal else 0

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (ki * block_k)
        if causal else (qi >= 0)
    )

    @pl.when(visible)
    def _compute():
        penalty = (
            _causal_penalty(qi, ki, block_q, block_k, offset)
            if causal else None
        )
        for hh in range(hpc):
            lo, hi = hh * d, (hh + 1) * d
            qs = (q_ref[:, lo:hi] * sm_scale).astype(q_ref.dtype)
            do = do_ref[:, lo:hi]
            s = _dot_tb(qs, k_ref[:, lo:hi])
            if causal:
                s = s + penalty
            lse128 = jnp.broadcast_to(lse_ref[:, hh:hh + 1],
                                      (block_q, _LANES))
            p = jnp.exp(s - _widen(lse128, block_k))
            p_lo = p.astype(do.dtype)
            dv_scr[:, lo:hi] = dv_scr[:, lo:hi] + _dot_ta(p_lo, do)
            dp = _dot_tb(do, v_ref[:, lo:hi])
            # delta = rowsum(do * o) for this head, recomputed in-register
            # (a VPU mult+rowsum, noise next to the dots) — a separate
            # XLA/Pallas delta pass costs more in relayouts/grid overhead
            # than it saves (measured round 4)
            delta = jnp.sum(
                do.astype(jnp.float32) * out_ref[:, lo:hi].astype(
                    jnp.float32),
                axis=-1, keepdims=True,
            )
            delta128 = jnp.broadcast_to(delta, (block_q, _LANES))
            ds = p * (dp - _widen(delta128, block_k))
            dk_scr[:, lo:hi] = dk_scr[:, lo:hi] + _dot_ta(
                ds.astype(qs.dtype), qs
            )

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel_packed(
    q_ref, do_ref, out_ref, lse_ref, k_ref, v_ref, dq_ref, dq_scr,
    delta_scr,
    *, sm_scale, block_q, block_k, causal, seq_q, seq_k, hpc, d,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)
        # per-head delta = rowsum(do * o), computed once per q block (the
        # do/out blocks are constant across the kj sweep, so their DMAs
        # amortize) instead of in a separate pass whose narrow output
        # needed a strided relayout per layer
        prod = do_ref[:].astype(jnp.float32) * out_ref[:].astype(
            jnp.float32)
        for hh in range(hpc):
            delta_scr[:, hh:hh + 1] = jnp.sum(
                prod[:, hh * d:(hh + 1) * d], axis=-1, keepdims=True
            )

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        penalty = (
            _causal_penalty(qi, kj, block_q, block_k, offset)
            if causal else None
        )
        for hh in range(hpc):
            lo, hi = hh * d, (hh + 1) * d
            qs = (q_ref[:, lo:hi] * sm_scale).astype(q_ref.dtype)
            do = do_ref[:, lo:hi]
            s = _dot_tb(qs, k_ref[:, lo:hi])
            if causal:
                s = s + penalty
            lse128 = jnp.broadcast_to(lse_ref[:, hh:hh + 1],
                                      (block_q, _LANES))
            p = jnp.exp(s - _widen(lse128, block_k))
            dp = _dot_tb(do, v_ref[:, lo:hi])
            delta128 = jnp.broadcast_to(delta_scr[:, hh:hh + 1],
                                        (block_q, _LANES))
            ds = (p * (dp - _widen(delta128, block_k))).astype(q_ref.dtype)
            dq_scr[:, lo:hi] = dq_scr[:, lo:hi] + lax.dot_general(
                ds, k_ref[:, lo:hi], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[:] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_packed(qf, kf, vf, do, out, lse_pk, *, n_heads, causal,
                      block_q, block_k, interpret, fused_qkv=False):
    """Packed grads. lse_pk: (b, n_packs, seq_q, hpc) fp32; out is the
    saved forward output — delta (rowsum(do*o) per head) is computed
    inside the kernels from do/out tiles whose DMAs ride the existing
    block schedule. fused_qkv: as in _flash_fwd_packed (dq/dk/dv still
    come back as three (b, s, h*d) arrays; the caller concatenates once
    for the projection backward)."""
    from jax.experimental.pallas import tpu as pltpu

    b, seq_q, hd = qf.shape
    if fused_qkv:
        hd //= 3
    seq_k = kf.shape[1]
    d = hd // n_heads
    hpc = _heads_per_pack(n_heads, d)
    w = hpc * d
    n_packs = n_heads // hpc
    koff = n_packs if fused_qkv else 0
    voff = 2 * n_packs if fused_qkv else 0
    block_q, block_k = _check_blocks(seq_q, seq_k, block_q, block_k, causal)
    sm_scale = 1.0 / (d ** 0.5)
    offset = seq_k - seq_q if causal else 0
    vis = _block_visible(block_q, block_k, offset)

    def qo_map(b_, g, j, i):
        return (b_, _redirect(causal, vis, i, j, i), g)

    def stat_map_dkdv(b_, g, j, i):
        return (b_, g, _redirect(causal, vis, i, j, i), 0)

    dkdv = functools.partial(
        _dkdv_kernel_packed, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, causal=causal, seq_q=seq_q, seq_k=seq_k,
        hpc=hpc, d=d,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(b, n_packs, seq_k // block_k, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, w), qo_map),
            pl.BlockSpec((None, block_q, w), qo_map),
            pl.BlockSpec((None, block_q, w), qo_map),
            pl.BlockSpec((None, None, block_q, hpc), stat_map_dkdv),
            pl.BlockSpec((None, block_k, w),
                         lambda b_, g, j, i: (b_, j, g + koff)),
            pl.BlockSpec((None, block_k, w),
                         lambda b_, g, j, i: (b_, j, g + voff)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, w), lambda b_, g, j, i: (b_, j, g)),
            pl.BlockSpec((None, block_k, w), lambda b_, g, j, i: (b_, j, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, seq_k, hd), kf.dtype),
            jax.ShapeDtypeStruct((b, seq_k, hd), vf.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, w), jnp.float32),
            pltpu.VMEM((block_k, w), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")
        ),
        interpret=interpret,
    )(qf, do, out, lse_pk, kf, vf)

    def k_map(b_, g, i, j):
        return (b_, _redirect(causal, vis, i, j, j), g + koff)

    def v_map(b_, g, i, j):
        return (b_, _redirect(causal, vis, i, j, j), g + voff)

    dqk = functools.partial(
        _dq_kernel_packed, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, causal=causal, seq_q=seq_q, seq_k=seq_k,
        hpc=hpc, d=d,
    )
    dq = pl.pallas_call(
        dqk,
        grid=(b, n_packs, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, w), lambda b_, g, i, j: (b_, i, g)),
            pl.BlockSpec((None, block_q, w), lambda b_, g, i, j: (b_, i, g)),
            pl.BlockSpec((None, block_q, w), lambda b_, g, i, j: (b_, i, g)),
            pl.BlockSpec((None, None, block_q, hpc),
                         lambda b_, g, i, j: (b_, g, i, 0)),
            pl.BlockSpec((None, block_k, w), k_map),
            pl.BlockSpec((None, block_k, w), v_map),
        ],
        out_specs=pl.BlockSpec((None, block_q, w),
                               lambda b_, g, i, j: (b_, i, g)),
        out_shape=jax.ShapeDtypeStruct((b, seq_q, hd), qf.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, w), jnp.float32),
            pltpu.VMEM((block_q, hpc), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")
        ),
        interpret=interpret,
    )(qf, do, out, lse_pk, kf, vf)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_packed(qf, kf, vf, n_heads, causal, block_q, block_k):
    out, _ = _flash_fwd_packed(
        qf, kf, vf, n_heads=n_heads, causal=causal, block_q=block_q,
        block_k=block_k, interpret=_interpret(),
    )
    return out


def _flash_packed_vjp_fwd(qf, kf, vf, n_heads, causal, block_q, block_k):
    out, lse_pk = _flash_fwd_packed(
        qf, kf, vf, n_heads=n_heads, causal=causal, block_q=block_q,
        block_k=block_k, interpret=_interpret(),
    )
    return out, (qf, kf, vf, out, lse_pk)


def _flash_packed_vjp_bwd(n_heads, causal, block_q, block_k, res, g_out):
    qf, kf, vf, out, lse_pk = res
    g_out = g_out.astype(qf.dtype)
    dq, dk, dv = _flash_bwd_packed(
        qf, kf, vf, g_out, out, lse_pk, n_heads=n_heads, causal=causal,
        block_q=block_q, block_k=block_k, interpret=_interpret(),
    )
    return dq, dk, dv


_flash_packed.defvjp(_flash_packed_vjp_fwd, _flash_packed_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _flash_packed_qkv(qkvf, n_heads, causal, block_q, block_k):
    out, _ = _flash_fwd_packed(
        qkvf, qkvf, qkvf, n_heads=n_heads, causal=causal, block_q=block_q,
        block_k=block_k, interpret=_interpret(), fused_qkv=True,
    )
    return out


def _flash_packed_qkv_vjp_fwd(qkvf, n_heads, causal, block_q, block_k):
    out, lse_pk = _flash_fwd_packed(
        qkvf, qkvf, qkvf, n_heads=n_heads, causal=causal, block_q=block_q,
        block_k=block_k, interpret=_interpret(), fused_qkv=True,
    )
    return out, (qkvf, out, lse_pk)


def _flash_packed_qkv_vjp_bwd(n_heads, causal, block_q, block_k, res, g_out):
    qkvf, out, lse_pk = res
    g_out = g_out.astype(qkvf.dtype)
    dq, dk, dv = _flash_bwd_packed(
        qkvf, qkvf, qkvf, g_out, out, lse_pk, n_heads=n_heads,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(), fused_qkv=True,
    )
    # one concatenate back to the projection layout — the only
    # materialized boundary op on the fused path (vs 3 slice fusions +
    # 6 relayout copies per layer on the sliced path)
    return (jnp.concatenate([dq, dk, dv], axis=-1),)


_flash_packed_qkv.defvjp(_flash_packed_qkv_vjp_fwd, _flash_packed_qkv_vjp_bwd)


def flash_attention_qkv(
    qkv: jnp.ndarray,  # (batch, seq, 3 * heads * head_dim)
    n_heads: int,
    *,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
) -> jnp.ndarray:
    """Fused self-attention straight off the QKV projection output.

    `qkv` is the flat (b, s, 3*h*d) activation the projection produces
    (column order [q heads | k heads | v heads] — exactly the row-major
    flatten of DenseGeneral's (3, h, d) features). The packed kernels
    window it at column offsets, so q/k/v are never sliced out: at
    lm_base shapes the sliced path paid ~4 ms/step in slice fusions and
    layout copies around the kernel boundary (round-4 profile), all of
    which this entry removes. Returns (b, s, h, d) like flash_attention.

    Requires packable head shapes (_heads_per_pack) and seq_q == seq_k
    (it IS self-attention); callers fall back to flash_attention with
    explicit slices otherwise."""
    b, s, three_hd = qkv.shape
    if three_hd % 3:
        raise ValueError(f"qkv last dim {three_hd} is not 3*h*d")
    hd = three_hd // 3
    d = hd // n_heads
    if _heads_per_pack(n_heads, d) is None:
        q, k, v = (
            qkv[..., :hd], qkv[..., hd:2 * hd], qkv[..., 2 * hd:]
        )
        rs = lambda x: x.reshape(b, s, n_heads, d)
        return flash_attention(
            rs(q), rs(k), rs(v), causal=causal, block_q=block_q,
            block_k=block_k,
        )
    out = _flash_packed_qkv(qkv, n_heads, causal, block_q, block_k)
    return out.reshape(b, s, n_heads, d)


# --------------------------------------------------------------------- #
# Differentiable entry points.
# _flash_lse returns (out, lse), both differentiable; the lse cotangent
# folds into delta (see module docstring). flash_attention drops lse.
# --------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return out, lse


def _flash_lse_vjp_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    g_out = g_out.astype(q.dtype)
    delta = jnp.sum(
        g_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None and not isinstance(
        g_lse, jax.custom_derivatives.SymbolicZero
    ):
        delta = delta - g_lse.astype(jnp.float32)
    dq, dk, dv = _flash_bwd(
        q, k, v, g_out, lse, delta,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=_interpret(),
    )
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(
    q: jnp.ndarray,  # (batch_heads, seq, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
):
    """Fused attention over folded (b*h, s, d) layout, returning (out, lse).

    lse is a differentiable output — the building block ring attention uses
    to merge per-ring-step partials (parallel/ring.py)."""
    return _flash_lse(q, k, v, causal, block_q, block_k)


def flash_attention(
    q: jnp.ndarray,  # (batch, seq, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
) -> jnp.ndarray:
    """Fused multi-head attention; layout-matches ops.attention._attention.

    Default blocks (512, 1024) are the measured sweet spot on TPU v5e for
    lm_base shapes (head_dim 64). Blocks clamp to the sequence length, so
    short-seq callers (ViT at s=64) are unaffected.

    When head_dim packs into 128 lanes (d <= 128 dividing 128, head count
    a multiple of the pack; or d a multiple of 128) the packed-layout
    kernels run directly on the flat (b, s, h*d) activations — no
    transposes at the model boundary (see the packed section above).
    Other shapes take the folded (b*h, s, d) path."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    if _heads_per_pack(h, d) is not None:
        out = _flash_packed(
            q.reshape(b, sq, h * d), k.reshape(b, sk, h * d),
            v.reshape(b, sk, h * d), h, causal, block_q, block_k,
        )
        return out.reshape(b, sq, h, d)

    def fold(x, s):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, x.shape[-1])

    out, _ = _flash_lse(
        fold(q, sq), fold(k, sk), fold(v, sk), causal, block_q, block_k
    )
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
