"""Numerical ops: losses, attention, and (pallas) custom kernels.

The reference's ops are all external CUDA/cuDNN kernels reached through
torch layer calls (SURVEY §2.2). Here the hot ops are XLA:TPU-compiled jnp
with pallas kernels where fusion matters.
"""

from ddp_practice_tpu.ops.losses import cross_entropy, accuracy_counts
from ddp_practice_tpu.ops.attention import dot_product_attention

__all__ = ["cross_entropy", "accuracy_counts", "dot_product_attention"]
