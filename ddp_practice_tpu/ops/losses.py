"""Loss and metric ops.

Replaces `nn.CrossEntropyLoss` (origin_main.py:86) and the eval
size/correct accumulators (ddp_main.py:96-112). Loss math runs in fp32
regardless of the compute dtype (the reference gets this from autocast's
fp32 loss policy; here it's explicit).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Mean softmax cross-entropy over the (global) batch.

    `weight` masks padded samples (0.0) so sums stay exact under sharded
    uneven batches — the exactness fix for the reference's padded-eval
    double counting (SURVEY §2.5).
    """
    loss_sum, weight_sum = cross_entropy_sum(
        logits, labels, weight=weight, label_smoothing=label_smoothing
    )
    if weight is None:
        return loss_sum / weight_sum
    return loss_sum / jnp.maximum(weight_sum, 1.0)


def cross_entropy_sum(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
) -> tuple:
    """(loss_sum, weight_sum) — the un-normalized pieces of cross_entropy.

    For accumulation schemes that see the batch in parts (the 1F1B
    pipeline schedule reduces per-microbatch sums and divides once at the
    end, parallel/pipeline_1f1b.py): sum(parts) / sum(weights) equals the
    global weighted mean exactly.
    """
    # Never materialize a (..., V) logprobs tensor: at LM vocab sizes it
    # is gigabytes of HBM per step. Instead nll = lse - logits[target]
    # where lse is a fused max + exp-sum reduction (reads the logits in
    # their storage dtype once per pass, fp32 accumulation) and the
    # target logit is a gather from the RAW logits. A dense one-hot
    # contraction (and the (V, V) eye behind it) is avoided for the same
    # reason. Smoothing folds in algebraically: the smoothed one-hot is
    # (1-ls)*target + ls/V, and mean(-logprobs) = lse - mean(logits) —
    # still no full-size intermediate.
    m = jnp.max(logits, axis=-1)
    lse = m.astype(jnp.float32) + jnp.log(
        jnp.sum(
            jnp.exp((logits - m[..., None]).astype(jnp.float32)), axis=-1
        )
    )
    tgt = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0].astype(jnp.float32)
    nll = lse - tgt
    if label_smoothing > 0.0:
        mean_logits = jnp.mean(logits.astype(jnp.float32), axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * (
            lse - mean_logits
        )
    if weight is None:
        return jnp.sum(nll), jnp.asarray(nll.size, jnp.float32)
    return jnp.sum(nll * weight), jnp.sum(weight)


def accuracy_counts(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
) -> tuple:
    """(correct, total) counts — the eval contract of ddp_main.py:99-109.

    Under GSPMD these sums over sharded arrays compile to global reductions
    (the `dist.reduce(SUM)` equivalent happens inside XLA).
    """
    pred = jnp.argmax(logits, axis=-1)
    match = (pred == labels).astype(jnp.float32)
    if weight is None:
        return jnp.sum(match), jnp.asarray(match.size, jnp.float32)
    return jnp.sum(match * weight), jnp.sum(weight)
