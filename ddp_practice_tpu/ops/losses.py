"""Loss and metric ops.

Replaces `nn.CrossEntropyLoss` (origin_main.py:86) and the eval
size/correct accumulators (ddp_main.py:96-112). Loss math runs in fp32
regardless of the compute dtype (the reference gets this from autocast's
fp32 loss policy; here it's explicit).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Mean softmax cross-entropy over the (global) batch.

    `weight` masks padded samples (0.0) so sums stay exact under sharded
    uneven batches — the exactness fix for the reference's padded-eval
    double counting (SURVEY §2.5).
    """
    loss_sum, weight_sum = cross_entropy_sum(
        logits, labels, weight=weight, label_smoothing=label_smoothing
    )
    if weight is None:
        return loss_sum / weight_sum
    return loss_sum / jnp.maximum(weight_sum, 1.0)


def cross_entropy_sum(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
) -> tuple:
    """(loss_sum, weight_sum) — the un-normalized pieces of cross_entropy.

    For accumulation schemes that see the batch in parts (the 1F1B
    pipeline schedule reduces per-microbatch sums and divides once at the
    end, parallel/pipeline_1f1b.py): sum(parts) / sum(weights) equals the
    global weighted mean exactly.
    """
    nll = _nll(logits, labels, float(label_smoothing))
    if weight is None:
        return jnp.sum(nll), jnp.asarray(nll.size, jnp.float32)
    return jnp.sum(nll * weight), jnp.sum(weight)


def _nll_forward(logits, labels, label_smoothing):
    # Never materialize a (..., V) logprobs tensor: at LM vocab sizes it
    # is gigabytes of HBM per step. Instead nll = lse - logits[target]
    # where lse is a fused max + exp-sum reduction (reads the logits in
    # their storage dtype once per pass, fp32 accumulation) and the
    # target logit is a gather from the RAW logits. A dense one-hot
    # contraction (and the (V, V) eye behind it) is avoided for the same
    # reason. Smoothing folds in algebraically: the smoothed one-hot is
    # (1-ls)*target + ls/V, and mean(-logprobs) = lse - mean(logits) —
    # still no full-size intermediate.
    m = jnp.max(logits, axis=-1)
    lse = m.astype(jnp.float32) + jnp.log(
        jnp.sum(
            jnp.exp((logits - m[..., None]).astype(jnp.float32)), axis=-1
        )
    )
    tgt = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0].astype(jnp.float32)
    nll = lse - tgt
    if label_smoothing > 0.0:
        mean_logits = jnp.mean(logits.astype(jnp.float32), axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * (
            lse - mean_logits
        )
    return nll, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _nll(logits, labels, label_smoothing):
    """Per-position nll with a hand-written backward.

    Autodiff of the max/gather form above works but pays an extra
    bookkeeping pass over the full logits (the max-VJP's argmax scatter
    and the gather-VJP — ~1.4 ms/step at lm_base/32k vocab, round-4
    profile). The closed form needs no third pass:
        d nll / d logits = softmax(logits) - y_smooth,
    y_smooth = (1-ls)*onehot + ls/V, with softmax recomputed from the
    saved lse — an elementwise expression XLA duplicates into the
    consuming matmul fusions, so the gradient tensor never hits HBM."""
    nll, _ = _nll_forward(logits, labels, label_smoothing)
    return nll


def _nll_vjp_fwd(logits, labels, label_smoothing):
    nll, lse = _nll_forward(logits, labels, label_smoothing)
    return nll, (logits, labels, lse)


def _nll_vjp_bwd(label_smoothing, res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    vocab = logits.shape[-1]
    onehot = labels[..., None] == jnp.arange(vocab, dtype=labels.dtype)
    if label_smoothing > 0.0:
        y = (
            (1.0 - label_smoothing) * onehot.astype(jnp.float32)
            + label_smoothing / vocab
        )
    else:
        y = onehot.astype(jnp.float32)
    dlogits = (g[..., None] * (p - y)).astype(logits.dtype)
    return dlogits, np.zeros(labels.shape, dtype=jax.dtypes.float0)


_nll.defvjp(_nll_vjp_fwd, _nll_vjp_bwd)


def accuracy_counts(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    weight: Optional[jnp.ndarray] = None,
) -> tuple:
    """(correct, total) counts — the eval contract of ddp_main.py:99-109.

    Under GSPMD these sums over sharded arrays compile to global reductions
    (the `dist.reduce(SUM)` equivalent happens inside XLA).
    """
    pred = jnp.argmax(logits, axis=-1)
    match = (pred == labels).astype(jnp.float32)
    if weight is None:
        return jnp.sum(match), jnp.asarray(match.size, jnp.float32)
    return jnp.sum(match * weight), jnp.sum(weight)
