"""Distributed tests on 8 virtual devices: the DDP-replacement contract.

Asserts the invariants of the reference's README checklist (SURVEY §4):
- data-parallel training runs over a real Mesh with sharded batches;
- replicated parameters stay bit-identical across devices after N steps
  (the DDP broadcast+all-reduce guarantee, ddp_main.py:121-123);
- DP training matches single-device training numerically on the same
  global batch (gradient all-reduce == large-batch gradient);
- eval reduction is global and exact under padding (fixes the reference's
  double-count, SURVEY §2.5).
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step
from ddp_practice_tpu.train.loop import fit
from ddp_practice_tpu.train.steps import make_eval_step


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.uniform(size=(n, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, n), jnp.int32),
        "weight": jnp.ones((n,), jnp.float32),
    }


def _make(mesh_cfg, devices=None):
    cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
    mesh = build_mesh(mesh_cfg, devices=devices)
    model = create_model("convnet")
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 28, 28, 1))

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, rng)
    shardings = shard_state(abstract, mesh)
    state = jax.jit(init_fn, out_shardings=shardings)(rng)
    bsh = batch_sharding(mesh)
    step = make_train_step(
        model, tx, mesh=mesh, state_shardings=shardings, batch_shardings=bsh
    )
    ev = make_eval_step(
        model, mesh=mesh, state_shardings=shardings, batch_shardings=bsh
    )
    return mesh, state, step, ev, bsh


@pytest.mark.fast
def test_dp8_runs_and_replicas_identical(devices):
    mesh, state, step, _, bsh = _make(MeshConfig(data=8))
    batch = {k: jax.device_put(v, bsh) for k, v in _batch(32).items()}
    for i in range(3):
        state, metrics = step(state, batch)
    # params are replicated: every device shard must be bit-identical
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    assert int(state.step) == 3


def test_dp_matches_single_device():
    """Same global batch, same init => same params after 2 steps, whether
    computed on 1 device or sharded over 8 (the all-reduce contract)."""
    batch = _batch(32, seed=3)

    mesh1, state1, step1, _, bsh1 = _make(
        MeshConfig(data=1), devices=jax.devices()[:1]
    )
    mesh8, state8, step8, _, bsh8 = _make(MeshConfig(data=8))

    b1 = {k: jax.device_put(v, bsh1) for k, v in batch.items()}
    b8 = {k: jax.device_put(v, bsh8) for k, v in batch.items()}
    for _ in range(2):
        state1, m1 = step1(state1, b1)
        state8, m8 = step8(state8, b8)

    p1 = jax.device_get(state1.params)
    p8 = jax.device_get(state8.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5),
        p1, p8,
    )
    np.testing.assert_allclose(
        float(m1["loss"]), float(m8["loss"]), rtol=1e-5
    )


def test_bn_stats_are_global_across_shards():
    """BatchNorm must normalize over the GLOBAL batch (SyncBatchNorm,
    ddp_main.py:120). With per-device batches drawn from different
    distributions, new running means must match the single-device run."""
    mesh8, state8, step8, _, bsh8 = _make(MeshConfig(data=8))
    mesh1, state1, step1, _, bsh1 = _make(
        MeshConfig(data=1), devices=jax.devices()[:1]
    )
    rng = np.random.default_rng(0)
    # deliberately heterogeneous across the batch: shard means differ
    img = np.concatenate(
        [rng.uniform(size=(4, 28, 28, 1)) * (i + 1) / 4.0 for i in range(8)]
    ).astype(np.float32)
    batch = {
        "image": jnp.asarray(img),
        "label": jnp.asarray(rng.integers(0, 10, 32), jnp.int32),
        "weight": jnp.ones((32,), jnp.float32),
    }
    b8 = {k: jax.device_put(v, bsh8) for k, v in batch.items()}
    b1 = {k: jax.device_put(v, bsh1) for k, v in batch.items()}
    state8, _ = step8(state8, b8)
    state1, _ = step1(state1, b1)
    s8 = jax.device_get(state8.batch_stats)
    s1 = jax.device_get(state1.batch_stats)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5),
        s1, s8,
    )


def test_eval_exact_under_padding():
    """Weighted eval ignores padded duplicates — exact where the reference
    double-counts (SURVEY §2.5)."""
    mesh, state, _, ev, bsh = _make(MeshConfig(data=8))
    batch = _batch(32, seed=1)
    batch["weight"] = jnp.asarray([1.0] * 20 + [0.0] * 12, jnp.float32)
    b = {k: jax.device_put(v, bsh) for k, v in batch.items()}
    correct, total = ev(state, b)
    assert float(total) == 20.0
    assert 0.0 <= float(correct) <= 20.0


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_fit_on_8_device_mesh():
    """End-to-end DP fit on the full mesh — the ddp_main.py-equivalent run."""
    cfg = TrainConfig(
        dataset="synthetic",
        epochs=1,
        batch_size=8,           # per replica -> global 64
        optimizer="adam",
        learning_rate=1e-3,
        precision="bf16",       # the "AMP" variant, TPU-style
        log_every_steps=0,
        mesh=MeshConfig(data=8),
    )
    summary = fit(cfg)
    assert summary["devices"] == 8
    assert summary["global_batch"] == 64
    assert summary["accuracy"] > 0.5, summary
