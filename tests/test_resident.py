"""Device-resident data path (data_placement='device'/'auto').

The corpus lives in HBM; epochs are driven by (steps, batch) int32 index
grids — the TPU-idiomatic endpoint of the reference's pinned-memory H2D
pipeline (origin_main.py:96,60-61): for corpora that fit on device there is
nothing left to transfer per step. These tests pin the load-bearing claim:
the resident path trains on exactly the host path's batches (same
(seed, epoch) plan) with agreement to float noise — see
_assert_params_close for why bitwise identity is out of reach.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.data import DataLoader, load_dataset
from ddp_practice_tpu.train.loop import Trainer


def _base(**kw):
    cfg = dict(
        dataset="synthetic", epochs=1, batch_size=4, optimizer="adam",
        learning_rate=1e-3, log_every_steps=0, mesh=MeshConfig(data=-1),
    )
    cfg.update(kw)
    return TrainConfig(**cfg)


def test_auto_placement_resolves_to_device_for_small_corpus(devices):
    tr = Trainer(_base())
    assert tr.resident_train_step is not None


def test_host_placement_keeps_streaming(devices):
    tr = Trainer(_base(data_placement="host"))
    assert tr.resident_train_step is None


@pytest.mark.fast
def test_epoch_plan_matches_iteration(devices):
    """epoch_plan is exactly the order __iter__ walks (same permutation,
    same wrap-padding, same weights)."""
    ds = load_dataset("synthetic", "./data", "train", synthetic_size=37)
    loader = DataLoader(ds, global_batch_size=8, seed=11, shuffle=True)
    loader.set_epoch(2)
    idx, w = loader.epoch_plan()
    assert idx.shape == (5, 8) and w.shape == (5, 8)
    assert idx.dtype == np.int32
    for step, batch in enumerate(loader):
        np.testing.assert_array_equal(batch["image"], ds.images[idx[step]])
        np.testing.assert_array_equal(batch["label"], ds.labels[idx[step]])
        np.testing.assert_array_equal(batch["weight"], w[step])
    # padded tail: zero weights, wrapped indices
    assert w[-1].sum() == 37 - 4 * 8


def _assert_params_close(a_state, b_state, atol):
    """The two paths run the same math on the same batches but compile as
    different XLA programs (scan-with-gather vs per-step), so reductions
    associate differently: agreement is to float noise, not bitwise — and
    float noise COMPOUNDS chaotically with steps (a 1-ulp grad difference
    perturbs the next forward, and so on). Measured on 8 devices with SGD:
    ~1e-7 after 16 steps, ~2e-5 after a 128-step epoch (original machine);
    this CI image's XLA CPU additionally re-partitions reductions by
    machine LOAD, measured up to ~1.4e-5 after 16 steps under a busy
    pytest parent. Tolerances allow that noise; a wrong-batch/layout bug
    produces diffs orders of magnitude past any of these (the bitwise
    first-step batch-stats pin above catches those directly)."""
    for a, b in zip(
        jax.tree.leaves(a_state.params), jax.tree.leaves(b_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol, rtol=0)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_resident_epoch_matches_host(devices):
    """One full epoch, resident vs host streaming: same batches (proven
    exactly by test_epoch_plan_matches_iteration), same step count, params
    equal to float noise; first-step BN batch stats are bit-identical
    (they depend only on the data, proving the gathered batches and the
    'data'-axis layout match the host path exactly)."""
    host = Trainer(_base(data_placement="host", optimizer="sgd",
                         learning_rate=1e-2, max_steps_per_epoch=1))
    host.train_epoch(0)
    res = Trainer(_base(data_placement="device", optimizer="sgd",
                        learning_rate=1e-2, max_steps_per_epoch=1))
    res.train_epoch(0)
    for a, b in zip(
        jax.tree.leaves(host.state.batch_stats),
        jax.tree.leaves(res.state.batch_stats),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    host2 = Trainer(_base(data_placement="host", optimizer="sgd",
                          learning_rate=1e-2, max_steps_per_epoch=16))
    host2.train_epoch(0)
    res2 = Trainer(_base(data_placement="device", optimizer="sgd",
                         learning_rate=1e-2, max_steps_per_epoch=16))
    res2.train_epoch(0)
    assert int(res2.state.step) == int(host2.state.step) == 16
    _assert_params_close(host2.state, res2.state, atol=1e-4)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_resident_whole_epoch_one_dispatch(devices):
    """steps_per_call=-1: the entire epoch is one scan call; step count and
    params still match the per-step host path (compounded float noise over
    a full 128-step epoch — see _assert_params_close)."""
    host = Trainer(_base(data_placement="host", optimizer="sgd",
                         learning_rate=1e-2))
    host.train_epoch(0)
    res = Trainer(_base(data_placement="device", steps_per_call=-1,
                        optimizer="sgd", learning_rate=1e-2))
    res.train_epoch(0)
    assert int(res.state.step) == int(host.state.step)
    _assert_params_close(host.state, res.state, atol=5e-4)


def test_resident_eval_matches_host(devices):
    """Exact weighted eval from the resident corpus == host eval, including
    the zero-weighted padded tail."""
    host = Trainer(_base(data_placement="host"))
    res = Trainer(_base(data_placement="device", steps_per_call=-1))
    assert res.evaluate() == host.evaluate()


def test_resident_respects_max_steps_cap(devices):
    tr = Trainer(_base(data_placement="device", max_steps_per_epoch=5))
    tr.train_epoch(0)
    assert int(tr.state.step) == 5


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_resident_fit_end_to_end(devices):
    """fit() through the resident path reaches the same accuracy contract
    and reports the same step count as the host path."""
    cfg = _base(data_placement="device", steps_per_call=-1, epochs=2)
    summary = Trainer(cfg).fit()
    assert np.isfinite(summary["accuracy"])
    assert summary["steps"] == 2 * (4096 // (4 * jax.device_count()))


def test_whole_epoch_requires_resident(devices):
    with pytest.raises(ValueError, match="steps_per_call=-1"):
        Trainer(_base(data_placement="host", steps_per_call=-1))


def test_invalid_steps_per_call_rejected():
    """Only K >= 1 or exactly -1: a typo like -2 or 0 must not silently
    train in per-step mode."""
    for bad in (-2, 0, -32):
        with pytest.raises(ValueError, match="steps_per_call"):
            TrainConfig(steps_per_call=bad)


def test_resident_group_capped_by_watchdog(devices):
    """With a watchdog enabled, whole-epoch groups are capped at the probe
    interval so a probe never blocks for compile+epoch with no beats."""
    tr = Trainer(_base(data_placement="device", steps_per_call=-1,
                       watchdog_timeout_s=300.0,
                       watchdog_probe_every_steps=10))
    assert tr._resident_group(128) == 10
    tr2 = Trainer(_base(data_placement="device", steps_per_call=-1))
    assert tr2._resident_group(128) == 128
