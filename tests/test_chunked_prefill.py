"""Sarathi-style chunked prefill (serve/engine.py `prefill_chunk`).

The contract: a chunk-admitted prompt lands in exactly the state a
whole-prompt admission leaves behind — same tokens out, same radix
publication, decode entirely chunk-blind — while each chunk is one
bounded `_prefix_prefill` dispatch so long prompts stop monopolizing
the decode loop (the TTFT win is measured by the frontdoor bench,
BENCH_serve.json `frontdoor_100rps.ttft_p99_ratio_chunked`). Config
misuse is rejected at construction; everything that compiles an engine
is `slow`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, PagedEngine, SlotEngine
from ddp_practice_tpu.serve.engine import warm_engine

VOCAB = 32

PKW = dict(max_slots=3, block_size=8, max_blocks_per_slot=12,
           prefix_cache=True)


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _run(eng, prompt, n=12, seed=0):
    """Admit, pump any pending prefill chunks, decode n tokens."""
    slot = eng.admit(prompt, seed=seed, max_positions=n)
    while getattr(eng, "is_prefilling", lambda s: False)(slot):
        eng.prefill_step(slot)
    out = []
    for _ in range(n):
        out.append(int(eng.step_burst()[0][slot]))
    eng.release(slot)
    return out


# ------------------------------------------------------ config validation
def test_chunk_config_gates(lm, devices):
    model, params = lm
    with pytest.raises(ValueError, match="prefix_cache"):
        PagedEngine(model, params, EngineConfig(
            **dict(PKW, prefix_cache=False), prefill_chunk=16))
    with pytest.raises(ValueError, match=">= 1"):
        PagedEngine(model, params, EngineConfig(**PKW, prefill_chunk=-4))
    with pytest.raises(ValueError, match="exceeds"):
        PagedEngine(model, params, EngineConfig(
            **PKW, prompt_buckets=(8,), prefill_chunk=16))
    # chunking is a paged-prefix mechanism; the slot engine refuses it
    with pytest.raises(ValueError, match="prefill_chunk"):
        SlotEngine(model, params, EngineConfig(
            max_slots=2, prompt_buckets=(8,), max_len=64,
            prefill_chunk=8))


# ----------------------------------------------------------- equivalence
@pytest.mark.slow
def test_chunked_prefill_matches_whole_prompt(lm, devices):
    """Token identity: the same long prompt through chunk-pumped
    prefill and through one whole-prompt dispatch. One retry for the
    image's XLA-CPU load nondeterminism (near-tied argmax over the toy
    model; same contract as tests/test_kv_pages.py) — a real
    divergence fails both attempts."""
    model, params = lm
    rng = np.random.default_rng(3)
    plain = PagedEngine(model, params, EngineConfig(
        **PKW, prompt_buckets=(8, 16, 64)))
    warm_engine(plain)
    chunked = PagedEngine(model, params, EngineConfig(
        **PKW, prefill_chunk=16))
    warm_engine(chunked)

    for attempt in range(2):
        prompt = rng.integers(1, VOCAB, 50).tolist()
        a = _run(plain, prompt)
        b = _run(chunked, prompt)
        if a == b:
            break
    assert a == b, (a, b)


@pytest.mark.slow
def test_chunk_pump_bounds_and_past_bucket_service(lm, devices,
                                                   compile_guard):
    """The pump runs at most ceil(len/chunk) bounded dispatches and
    the final one activates the slot; chunking also makes prompts past
    the largest bucket servable (each chunk buckets individually) —
    and none of this churn compiles anything after warmup."""
    model, params = lm
    rng = np.random.default_rng(4)
    eng = PagedEngine(model, params, EngineConfig(
        **PKW, prefill_chunk=16))
    warm_engine(eng)

    prompt = rng.integers(1, VOCAB, 50).tolist()
    slot = eng.admit(prompt, seed=0, max_positions=4)
    assert eng.is_prefilling(slot)
    pumps = 0
    while eng.is_prefilling(slot):
        done = eng.prefill_step(slot)
        pumps += 1
        assert done == (not eng.is_prefilling(slot))
    assert pumps <= -(-len(prompt) // 16)
    for _ in range(4):
        eng.step_burst()
    eng.release(slot)

    # past the largest warm bucket: unservable whole, servable chunked
    plain = PagedEngine(model, params, EngineConfig(
        **PKW, prompt_buckets=(8, 16, 64)))
    assert not plain.fits_prompt(90)
    assert eng.fits_prompt(90)
    big = rng.integers(1, VOCAB, 90).tolist()
    assert len(_run(eng, big, n=4)) == 4

    with compile_guard(eng):
        _run(eng, rng.integers(1, VOCAB, 40).tolist(), n=4)
