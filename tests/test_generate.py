"""Inference path (inference.py): KV-cache decode + sampling.

The reference trains and stops (origin_main.py:113) — no inference exists
to cite. Pinned here: the cached incremental decode computes EXACTLY the
same logits as the full forward pass (the cache is an optimization, not an
approximation), greedy generation matches a naive re-run-the-whole-prompt
rollout, sampling is deterministic under a fixed PRNG key, and the EOS
done-mask pads everything after the first EOS.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.inference import (
    decode_bytes,
    encode_bytes,
    make_cache,
    make_generate_fn,
    sample_logits,
)
from ddp_practice_tpu.models import create_model

VOCAB = 32


def _tiny_lm(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_len", 64)
    kw.setdefault("hidden_dim", 64)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 128)
    return create_model("lm_tiny", **kw)


@pytest.fixture(scope="module")
def lm():
    model = _tiny_lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params


def test_cached_decode_matches_full_forward(devices, lm):
    """Prefill + one-token steps reproduce the full forward's logits."""
    model, params = lm
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    full = model.apply({"params": params}, tokens)

    prompt_len, total = 5, 12
    cache = make_cache(model, 2, total)
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        tokens[:, :prompt_len],
        decode=True,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :prompt_len]),
        rtol=2e-5, atol=2e-5,
    )
    cache = mut["cache"]
    for t in range(prompt_len, total):
        step_logits, mut = model.apply(
            {"params": params, "cache": cache},
            tokens[:, t:t + 1],
            decode=True,
            mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full[:, t]),
            rtol=2e-5, atol=2e-5,
        )


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_greedy_generate_matches_naive_rollout(devices, lm):
    """The scan-over-cache generate == re-running the full model each step."""
    model, params = lm
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    n_new = 10
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n_new, temperature=0.0))
    fast = np.asarray(gen(params, prompt))

    seq = prompt
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(fast, np.asarray(seq))


def test_sampling_deterministic_under_key(devices, lm):
    model, params = lm
    prompt = jnp.asarray([[7, 7, 7]], jnp.int32)
    gen = jax.jit(
        make_generate_fn(model, max_new_tokens=8, temperature=1.3, top_k=8)
    )
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(42)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(42)))
    np.testing.assert_array_equal(a, b)
    # prompt survives verbatim
    np.testing.assert_array_equal(a[:, :3], np.asarray(prompt))


def test_eos_pads_tail(devices, lm):
    """Everything after the first emitted EOS is pad_id."""
    model, params = lm
    prompt = jnp.asarray([[2, 9]], jnp.int32)
    n_new = 12
    greedy = np.asarray(
        jax.jit(make_generate_fn(model, max_new_tokens=n_new, temperature=0.0))(
            params, prompt
        )
    )
    # whatever greedy emits first becomes the EOS token of a second run
    eos = int(greedy[0, 2])
    pad = VOCAB - 1
    out = np.asarray(
        jax.jit(
            make_generate_fn(
                model, max_new_tokens=n_new, temperature=0.0,
                eos_id=eos, pad_id=pad,
            )
        )(params, prompt)
    )
    assert out[0, 2] == eos  # the EOS itself is emitted...
    np.testing.assert_array_equal(
        out[0, 3:], np.full(n_new - 1, pad)
    )  # ...and the rest is padding


@pytest.mark.fast
def test_sample_logits_filters(devices):
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    key = jax.random.PRNGKey(0)
    assert int(sample_logits(logits, None, temperature=0.0)[0]) == 1
    # top_k=1 and a tiny top_p both collapse to argmax regardless of key
    for k in range(5):
        kk = jax.random.PRNGKey(k)
        assert int(sample_logits(logits, kk, top_k=1)[0]) == 1
        assert int(sample_logits(logits, kk, top_p=1e-6)[0]) == 1
    # full top_p keeps the distribution samplable (any valid index)
    assert 0 <= int(sample_logits(logits, key, top_p=0.99)[0]) < 4


@pytest.mark.fast
def test_sample_logits_topk_then_topp_bf16(devices):
    """Filter COMPOSITION on bf16 logits: k first, then p (the docstring
    contract), with the fp32 upcast before the filter math.

    logits [10, 8, 6, 4]: raw softmax ~[.865, .117, .016, .002]; after
    top_k=2 the renormalized top token carries ~.8808. top_p=0.88 sits
    between those two masses, so the order is observable: k-then-p drops
    the runner-up (exclusive mass before it .8808 > .88 under fp32 math)
    and EVERY draw is the argmax; p-then-k would keep it (.865 < .88)
    and the runner-up would appear with ~12% probability per draw.
    The same threshold also pins the upcast: bf16 cumsum rounds .8808
    down to .8789 < .88 and would keep the runner-up too."""
    logits = jnp.asarray([[10.0, 8.0, 6.0, 4.0]], jnp.bfloat16)
    for s in range(40):
        key = jax.random.PRNGKey(s)
        assert int(sample_logits(logits, key, top_k=2, top_p=0.88)[0]) == 0
    # with p above both masses the top-2 set survives intact (and ONLY
    # the top-2: k already removed the rest)
    seen = {
        int(sample_logits(logits, jax.random.PRNGKey(s),
                          top_k=2, top_p=0.95)[0])
        for s in range(200)
    }
    assert seen == {0, 1}
    # the argmax always survives top_p, however tiny p is and whatever
    # the temperature did to the bf16 logits first
    for s in range(40):
        key = jax.random.PRNGKey(s)
        assert int(sample_logits(
            logits, key, temperature=2.5, top_p=1e-6
        )[0]) == 0


@pytest.mark.fast
def test_byte_codec_roundtrip(devices):
    s = "hello, TPU\n"
    assert decode_bytes(encode_bytes(s)[0]) == s


def test_generate_rejects_overflow(devices, lm):
    model, params = lm  # max_len 64
    prompt = jnp.zeros((1, 60), jnp.int32)
    gen = make_generate_fn(model, max_new_tokens=8, temperature=0.0)
    with pytest.raises(ValueError, match="max_len"):
        gen(params, prompt)


def test_generate_with_tensor_sharded_params(devices, lm):
    """Multi-chip inference: generation with Megatron-sharded params (and
    the batch over 'data') produces exactly the unsharded tokens — the
    KV cache lives inside the jit, so GSPMD shards it (heads dim) by
    propagation from the sharded Q/K/V."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.tree_util import tree_map_with_path

    from ddp_practice_tpu.config import MeshConfig
    from ddp_practice_tpu.parallel.mesh import build_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules

    model, params = lm  # 4 heads; tensor=4 gives 1 head per shard
    prompt = jnp.asarray([[5, 2, 7], [1, 1, 1]], jnp.int32)
    gen = jax.jit(make_generate_fn(model, max_new_tokens=8, temperature=0.0))
    want = np.asarray(gen(params, prompt))

    mesh = build_mesh(MeshConfig(data=2, tensor=4))
    rules = param_sharding_rules("lm_tiny")
    sharded = tree_map_with_path(
        lambda p, leaf: jax.device_put(
            leaf, NamedSharding(mesh, rules(p, leaf) or P())
        ),
        params,
    )
    qkv = sharded["block0"]["attn"]["qkv"]["kernel"]
    assert qkv.addressable_shards[0].data.shape[2] == 1  # heads really split
    prompt_sharded = jax.device_put(
        prompt, NamedSharding(mesh, P(MeshConfig.AXIS_DATA))
    )
    got = np.asarray(gen(sharded, prompt_sharded))
    np.testing.assert_array_equal(got, want)


def test_variable_length_prompts_match_per_prompt_runs(devices):
    """Left-padded variable-length batching (pad_left_prompts +
    prompt_lens): every sequence's greedy continuation must equal its own
    single-prompt run — padding must be invisible (RoPE model; the
    attention mask hides pad K/V, rotary positions are shift-invariant)."""
    from ddp_practice_tpu.inference import pad_left_prompts

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=64, hidden_dim=64, depth=2,
        num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompts = [[3, 1, 4], [2, 7, 1, 8, 2], [5]]
    tokens, lens = pad_left_prompts(prompts)
    n_new = 6
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n_new, temperature=0.0))
    batched = np.asarray(gen(params, tokens, None, lens))
    width = tokens.shape[1]
    for i, p in enumerate(prompts):
        single = np.asarray(gen(params, jnp.asarray([p], jnp.int32)))
        np.testing.assert_array_equal(batched[i, width:], single[0, len(p):])


def test_variable_length_needs_rope(devices, lm):
    """attn_start with learned absolute positions must raise (padding
    would shift every real token's position)."""
    model, params = lm  # learned positions
    prompt = jnp.asarray([[0, 0, 3, 1]], jnp.int32)
    gen = make_generate_fn(model, max_new_tokens=2, temperature=0.0)
    with pytest.raises(ValueError, match="rope"):
        gen(params, prompt, None, jnp.asarray([2], jnp.int32))


def test_bf16_kv_cache_tracks_fp32_cache(devices, lm):
    """kv_cache_dtype=bf16 under an fp32 policy: the cache stores rounded
    K/V but the decode logits stay within bf16 rounding of the fp32-cache
    path (the cache is storage, not math — attention still promotes)."""
    model, params = lm
    model_bf16 = _tiny_lm(kv_cache_dtype=jnp.bfloat16)
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)

    def roll(m):
        cache = make_cache(m, 2, 12)
        assert (
            cache["block0"]["attn"]["cached_key"].dtype
            == (jnp.bfloat16 if m is model_bf16 else jnp.float32)
        )
        logits, mut = m.apply(
            {"params": params, "cache": cache},
            tokens[:, :5], decode=True, mutable=["cache"],
        )
        outs = [logits]
        for t in range(5, 12):
            logits, mut = m.apply(
                {"params": params, "cache": mut["cache"]},
                tokens[:, t:t + 1], decode=True, mutable=["cache"],
            )
            outs.append(logits)
        return np.concatenate([np.asarray(o) for o in outs], axis=1)

    np.testing.assert_allclose(
        roll(model_bf16), roll(model), rtol=5e-2, atol=3e-2
    )


def test_bf16_param_stream_bit_identical(devices):
    """Streaming bf16-cast params under the bf16 policy generates EXACTLY
    the fp32-master tokens and logit-path bits: every layer casts its fp32
    kernel to bf16 at compute time anyway, so the one-time cast commutes
    (this is what lets generate.py/bench halve decode HBM traffic for
    free)."""
    from ddp_practice_tpu.config import PrecisionPolicy
    from ddp_practice_tpu.inference import cast_params_for_streaming

    model = _tiny_lm(policy=PrecisionPolicy.bf16())
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    cast = cast_params_for_streaming(params)
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    gen = jax.jit(make_generate_fn(model, max_new_tokens=12, temperature=0.0))
    np.testing.assert_array_equal(
        np.asarray(gen(params, prompt)), np.asarray(gen(cast, prompt))
    )


def test_generate_rejects_empty_prompt(devices, lm):
    model, params = lm
    gen = make_generate_fn(model, max_new_tokens=4, temperature=0.0)
    with pytest.raises(ValueError, match="at least one token"):
        gen(params, jnp.zeros((1, 0), jnp.int32))
