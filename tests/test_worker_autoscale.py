"""Elastic fleet e2e: REAL worker processes through a full scale cycle.

The ISSUE-14 acceptance teeth. One fleet, one story: a 1-worker fleet
takes a burst it cannot absorb -> the autoscaler trips fast and
promotes a PRE-WARMED standby (milliseconds, not the ~15 s cold spawn)
-> the burst drains and the resolve-slow path scales back down via the
graceful SIGTERM drain -> chaos SIGKILLs the DRAINING worker
mid-scale-down. The contract that must survive all of it:

- zero lost requests, every completion greedy token-identical to the
  fault-free oracle;
- the shrunk slot retires WITHOUT a restart-budget charge or a respawn
  (a drain death is a goodbye, not a crash);
- the merged trace timeline validates clean in fleet mode and carries
  the scale_up / scale_down instants on the router lane;
- tools/check_stream.py audits the run's telemetry to 0 violations
  (exactly-once delivery held across the scale events).

Host-pure pins of every policy transition live in
tests/test_serve_autoscaler.py; the supervisor actuator pins in
tests/test_worker_supervisor.py. Real workers cost ~15 s each on this
one-core image: slow + chaos.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ddp_practice_tpu.serve.autoscaler import Autoscaler, AutoscalerConfig
from ddp_practice_tpu.serve.engine import EngineConfig
from ddp_practice_tpu.serve.scheduler import Request, Scheduler
from ddp_practice_tpu.serve.supervisor import (
    DRAINING,
    STOPPED,
    SupervisorConfig,
    make_fleet_router,
)
from ddp_practice_tpu.serve.worker import WorkerSpec, build_model
from ddp_practice_tpu.utils.telemetry import TelemetryExporter
from ddp_practice_tpu.utils.trace import ROUTER_PID, TraceRecorder
from tools.check_traces import validate, validate_fleet

pytestmark = pytest.mark.slow

MODEL_KW = {"vocab_size": 64, "max_len": 128, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 128, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}
SPEC = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW, max_queue=64,
                  trace=True)
SUP_CFG = SupervisorConfig(restart_base_s=0.25, restart_budget=5,
                           ready_timeout_s=300.0,
                           shrink_kill_after_s=60.0)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace(n=8, seed=5):
    rng = np.random.default_rng(seed)
    # long decode budgets keep the fleet busy for seconds on the 1-core
    # box — the burst must outlive the control loop's reaction
    return [{
        "rid": i,
        "prompt": rng.integers(1, 64, int(rng.integers(3, 9))).tolist(),
        "max_new_tokens": int(rng.integers(60, 81)),
    } for i in range(n)]


def _expected_tokens(trace):
    """Fault-free greedy oracle: one in-process scheduler, same model."""
    model, params = build_model(MODEL_KW)
    eng_kw = dict(ENGINE_KW)
    eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
    from ddp_practice_tpu.serve.engine import SlotEngine

    engine = SlotEngine(model, params, EngineConfig(**eng_kw))
    sched = Scheduler(engine, max_queue=64)
    for t in trace:
        sched.submit(Request(**t))
    comps = sched.run_until_idle()
    assert all(c.status == "length" for c in comps)
    return {c.rid: list(c.tokens) for c in comps}


def _tolerate_load_flake(attempt, tries=2):
    for i in range(tries):
        try:
            return attempt()
        except AssertionError:
            if i == tries - 1:
                raise


@pytest.mark.chaos
def test_burst_scaleup_drain_down_chaos_sigkill_exactly_once(tmp_path):
    def attempt():
        trace = _trace(n=8, seed=5)
        expected = _expected_tokens(trace)
        tracer = TraceRecorder()
        tpath = str(tmp_path / "autoscale_run.jsonl")
        exporter = TelemetryExporter(tpath, start=False)
        router, sup, handles = make_fleet_router(
            SPEC, 1, sup_config=SUP_CFG, tracer=tracer,
            telemetry=exporter,
        )
        asc = Autoscaler(
            router, sup, SPEC,
            config=AutoscalerConfig(
                min_size=1, max_size=2, eval_interval_s=0.2,
                up_pressure=1.5, down_pressure=0.5,
                hold_s=1.0, cooldown_up_s=0.5, cooldown_down_s=0.5,
                down_stable_s=0.5, standby_target=1,
            ),
            clock=router.clock,
        )
        router.autoscaler = asc
        try:
            # the pool pays the ~15 s import+warm bill AHEAD of demand
            assert asc.pool.wait_ready(timeout_s=300.0, n=1), \
                f"standby never warmed: {asc.pool.spawn_errors}"

            # ---- burst: 8 requests onto 2 decode slots = pressure 4.0
            for t in trace:
                assert router.submit(Request(**t))
            deadline = time.monotonic() + 60
            while not asc.events:
                assert time.monotonic() < deadline, "never scaled up"
                router.step()
            up = asc.events[0]
            assert up["direction"] == "up"
            assert up["trigger"] == "queue_pressure"
            # the promotion came WARM from the pool, in milliseconds —
            # the reactive-cold alternative is the 15 s it just skipped
            assert up["warm"] is True
            assert up["join_s"] < 2.0
            assert sup.active_slots() == 2
            assert len(router.handles) == 2
            grown = up["slot"]

            # ---- the burst completes across BOTH workers, zero lost,
            # greedy token-identical to the fault-free oracle
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            for rid, want in expected.items():
                assert by_rid[rid].tokens == want, f"rid {rid} diverged"
            assert any(h.id == grown and h._stats
                       for h in router.handles), \
                "the promoted worker never served"

            # ---- burst over: resolve slow -> graceful drain begins
            deadline = time.monotonic() + 60
            while len(asc.events) < 2:
                assert time.monotonic() < deadline, "never scaled down"
                router.step()
                time.sleep(0.02)
            down = asc.events[1]
            assert down["direction"] == "down"
            assert down["trigger"] == "slo_resolved"
            victim = down["slot"]
            assert victim == grown                 # newest leaves first
            assert sup.state(victim) == DRAINING
            assert asc.snapshot()["draining"] == [victim]

            # ---- chaos: SIGKILL the DRAINING worker mid-scale-down
            sup.kill(victim, "SIGKILL")
            deadline = time.monotonic() + 60
            while len(router.handles) != 1:
                assert time.monotonic() < deadline, "never retired"
                router.step()
                time.sleep(0.02)
            assert sup.state(victim) == STOPPED    # retired, not FAILED
            assert sup.restarts[victim] == 0       # no budget charge
            assert asc.drain_log[-1]["slot"] == victim
            assert asc.snapshot()["size"] == 1
            # no respawn ever comes for a shrunk slot
            time.sleep(1.0)
            sup.poll()
            assert sup.state(victim) == STOPPED

            # ---- the survivor still serves
            router.submit(Request(rid=999, prompt=[1, 2, 3],
                                  max_new_tokens=4))
            tail = router.run_until_idle()
            assert {c.rid: c.status for c in tail}[999] == "length"
        finally:
            asc.close()
            sup.stop()
            exporter.pump()
            exporter.close()

        # ---- one validator-clean merged timeline, scale story included
        chrome = tracer.to_chrome_trace()
        assert validate(chrome) == []
        assert validate_fleet(chrome) == []
        ev = chrome["traceEvents"]
        instants = {e["name"] for e in ev if e.get("ph") == "i"
                    and e.get("pid") == ROUTER_PID}
        assert {"scale_up", "scale_down", "scale_down_done"} <= instants
        ups = [e for e in ev if e.get("ph") == "i"
               and e["name"] == "scale_up"]
        assert ups and all(e["args"]["warm"] for e in ups)

        # ---- exactly-once across the whole cycle: 0 violations
        r = subprocess.run(
            [sys.executable, "tools/check_stream.py", tpath],
            capture_output=True, text=True, cwd=ROOT, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "STREAMS OK" in r.stdout

    _tolerate_load_flake(attempt)
