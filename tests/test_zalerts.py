"""Push alerts: sink specs, retry backoff, the dead-sink breaker, and
fleet-level edges — all host-pure (injected clock + transport), plus
real jsonl/command deliveries (no network).

The backoff schedule is pinned against utils/backoff.py backoff_delay
itself (the shared-schedule contract every retry loop in this repo
holds). The breaker is HALF-OPEN: a tripped sink keeps exactly one
queued edge (the newest) and re-probes it every probe_cooldown_s — the
schedule, the single-edge queue, and the recovery path are all pinned
here on an injected clock.
"""

import json

import pytest

from ddp_practice_tpu.serve.slo import (
    AlertSinkSpec,
    AlertSinks,
    FleetAlerts,
    SLOConfig,
    SLOWatchdog,
)
from ddp_practice_tpu.utils.backoff import backoff_delay
from ddp_practice_tpu.utils.metrics import MetricsRegistry


# ------------------------------------------------------------ spec parsing
def test_sink_spec_parse_forms():
    assert AlertSinkSpec.parse("jsonl:/tmp/a.jsonl") == AlertSinkSpec(
        "jsonl", "/tmp/a.jsonl")
    assert AlertSinkSpec.parse("command:notify -u ops") == AlertSinkSpec(
        "command", "notify -u ops")
    # a bare URL is a webhook; the colon inside survives
    s = AlertSinkSpec.parse("http://pager.example:8080/hook")
    assert s.kind == "webhook" and s.target.endswith(":8080/hook")
    s = AlertSinkSpec.parse("webhook:https://h/x")
    assert (s.kind, s.target) == ("webhook", "https://h/x")
    with pytest.raises(ValueError):
        AlertSinkSpec.parse("bogus")
    with pytest.raises(ValueError):
        AlertSinkSpec.parse("smoke:signals")


# ----------------------------------------------------- backoff + breaker
class _Clock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t


def test_retry_backoff_matches_shared_schedule():
    clock = _Clock()
    attempts = []
    sinks = AlertSinks(["command:x"], clock=clock, max_failures=10,
                       base_s=0.5, max_s=30.0, seed=3,
                       deliver=lambda s, e: attempts.append(clock.t)
                       and False or False)
    sinks.send({"event": "trip"})
    assert attempts == [0.0]
    # the k-th retry comes due exactly at the shared backoff_delay sum
    due = 0.0
    for k in range(3):
        due += backoff_delay(k, base_s=0.5, max_s=30.0, seed=3)
        clock.t = due - 1e-6
        sinks.flush()
        assert len(attempts) == k + 1          # just before: not due
        clock.t = due
        sinks.flush()
        assert len(attempts) == k + 2          # at the edge: retried


def test_dead_sink_breaker_goes_half_open_with_one_kept_edge():
    clock = _Clock()
    calls = []
    reg = MetricsRegistry()
    sinks = AlertSinks(["command:x", "jsonl:y"], clock=clock,
                       registry=reg, max_failures=2, base_s=0.1,
                       seed=0,
                       deliver=lambda s, e: (calls.append(s.kind),
                                             s.kind == "jsonl")[1])
    sinks.send({"event": "trip", "objective": "a"})
    clock.t = 10.0
    sinks.flush()
    st = {s["sink"]: s for s in sinks.state()}
    # tripped — but half-open: exactly ONE edge stays queued for the
    # probe (the breaker sheds the backlog, not the comeback path)
    assert st["command:x"]["dead"] and st["command:x"]["pending"] == 1
    assert not st["jsonl:y"]["dead"] and st["jsonl:y"]["delivered"] == 1
    n = len(calls)
    # a send while dead REPLACES the kept edge (newest wins, displaced
    # edge counts as dropped) and does not wake the sink early
    sinks.send({"event": "trip", "objective": "b"})
    assert calls[n:] == ["jsonl"]
    st = {s["sink"]: s for s in sinks.state()}
    assert st["command:x"]["pending"] == 1
    assert st["command:x"]["dropped"] == 1
    # no probe before the cool-down edge...
    n = len(calls)
    clock.t = 10.0 + sinks.probe_cooldown_s - 1e-6
    sinks.flush()
    assert calls[n:] == []
    # ...exactly one probe attempt AT it; failure stays dead and
    # re-arms the FIXED cool-down (no exponential schedule for probes)
    clock.t = 10.0 + sinks.probe_cooldown_s
    sinks.flush()
    assert calls[n:] == ["command"]
    assert {s["sink"]: s for s in sinks.state()}["command:x"]["dead"]
    n = len(calls)
    clock.t += sinks.probe_cooldown_s - 1e-6
    sinks.flush()
    assert calls[n:] == []
    assert sinks.any_alive


def test_dead_sink_recovers_via_half_open_probe():
    clock = _Clock()
    back = {"up": False}
    calls = []
    sinks = AlertSinks(["command:x"], clock=clock, max_failures=1,
                       base_s=0.1, seed=0,
                       deliver=lambda s, e: (calls.append(dict(e)),
                                             back["up"])[1])
    sinks.send({"event": "trip", "objective": "a"})
    assert not sinks.any_alive            # one failure trips at cap 1
    sinks.send({"event": "trip", "objective": "b"})
    sinks.send({"event": "resolve", "objective": "b"})
    s = sinks.state()[0]
    assert s["pending"] == 1 and s["dropped"] == 2
    back["up"] = True                     # the pager comes back
    n = len(calls)
    clock.t = sinks.probe_cooldown_s      # cool-down from the t=0 trip
    sinks.flush()
    # the probe delivered the NEWEST edge (current state of the world,
    # not the stale alarm) and closed the breaker
    assert [e["event"] for e in calls[n:]] == ["resolve"]
    s = sinks.state()[0]
    assert not s["dead"] and s["pending"] == 0 and s["failures"] == 0
    assert sinks.any_alive
    # alive again for subsequent sends — straight-through delivery
    sinks.send({"event": "trip", "objective": "c"})
    assert calls[-1]["objective"] == "c"
    assert sinks.state()[0]["delivered"] == 2


def test_pending_queue_is_bounded():
    sinks = AlertSinks(["command:x"], clock=lambda: 0.0,
                       max_failures=10**9, base_s=10.0,
                       deliver=lambda s, e: False)
    for i in range(AlertSinks.PENDING_CAP + 7):
        sinks.send({"event": "trip", "i": i})
    s = sinks.state()[0]
    assert s["pending"] == AlertSinks.PENDING_CAP
    assert s["dropped"] >= 7


# ------------------------------------------------------- real transports
def test_jsonl_and_command_delivery(tmp_path):
    path = tmp_path / "alerts.jsonl"
    sinks = AlertSinks([f"jsonl:{path}", "command:true"],
                       clock=lambda: 0.0)
    sinks.send({"kind": "alert", "event": "trip", "objective": "x"})
    sinks.send({"kind": "alert", "event": "resolve", "objective": "x"})
    lines = [json.loads(x) for x in path.read_text().splitlines()]
    assert [x["event"] for x in lines] == ["trip", "resolve"]
    st = {s["sink"]: s for s in sinks.state()}
    assert st["command:true"]["delivered"] == 2
    # a command that exits nonzero is a failed attempt
    bad = AlertSinks(["command:false"], clock=lambda: 0.0,
                     max_failures=1)
    bad.send({"event": "trip"})
    assert not bad.any_alive


# ------------------------------------------------------ watchdog wiring
def test_slo_watchdog_pushes_edges_to_sinks():
    clock = _Clock()
    got = []
    sinks = AlertSinks(["jsonl:unused"], clock=clock,
                       deliver=lambda s, e: got.append(dict(e)) or True)
    wd = SLOWatchdog(
        SLOConfig(error_rate=0.1, fast_window_s=1.0, slow_window_s=2.0,
                  min_events=3, trip_burn=2.0, resolve_burn=1.0),
        clock=clock, sinks=sinks,
    )
    for i in range(6):
        wd.observe_event(t=clock.t, status="error")
        clock.t += 0.05
    wd.evaluate(clock.t, force=True)
    assert wd.active
    trips = [e for e in got if e["event"] == "trip"]
    assert trips and trips[0]["objective"] == "error_rate"
    assert trips[0]["scope"] == "slo"
    # resolve edge pushes too
    clock.t += 3.0
    wd.evaluate(clock.t, force=True)
    assert not wd.active
    assert any(e["event"] == "resolve" for e in got)


# ------------------------------------------------------- fleet federation
def test_fleet_alerts_edges_on_status_transitions():
    clock = _Clock()
    got = []
    reg = MetricsRegistry()
    sinks = AlertSinks(["jsonl:x"], clock=clock,
                       deliver=lambda s, e: got.append(dict(e)) or True)
    fa = FleetAlerts(sinks, registry=reg, clock=clock)
    hz = {"workers": {"0": {"status": "healthy"},
                      "1": {"status": "healthy"}}}
    assert fa.observe(hz) == []
    hz["workers"]["1"]["status"] = "stale"
    assert [e["objective"] for e in fa.observe(hz)] == ["worker_stale"]
    # stale -> dead: trips the new objective AND resolves the old one
    hz["workers"]["1"]["status"] = "dead"
    edges = fa.observe(hz)
    assert {(e["event"], e["objective"]) for e in edges} == {
        ("trip", "worker_dead"), ("resolve", "worker_stale")}
    hz["workers"]["1"]["status"] = "healthy"
    assert [(e["event"], e["objective"]) for e in fa.observe(hz)] == [
        ("resolve", "worker_dead")]
    assert reg.counter("fleet_alerts_total").value == 2
    assert len([e for e in got if e["scope"] == "fleet"]) == len(got)
    # trip/resolve pairing held across the whole episode
    trips = [e for e in got if e["event"] == "trip"]
    resolves = [e for e in got if e["event"] == "resolve"]
    assert {e["objective"] for e in trips} == {
        e["objective"] for e in resolves}
