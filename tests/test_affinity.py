"""Cache-aware routing (serve/affinity.py): the host-pure half.

The contract under test, in layers:

- the HASH NAMESPACE: worker (radix-tree walk) and router (prompt
  walk) must compute identical names for identical block-aligned
  prefixes, or the whole scheme silently scores zero;
- the DIGEST WIRE: delta frames apply in order, a broken chain marks
  the view stale-until-full (never wrong), a worker restart's new
  epoch drops the dead tree's fingerprint, and freshness decays;
- the POLICY: affinity wins when a digest says a replica is warm,
  load wins outright past the imbalance cap, rendezvous homes
  first-seen families stably across membership churn, and with no
  usable digest the order is BYTE-IDENTICAL to the classic
  least-loaded sort — cache-awareness must be a strict overlay;
- the ROUTER: a reconciled "refused" completion (the one-way submit's
  draining-worker answer, serve/supervisor.py) re-dispatches with no
  breaker mark and no retry charge.

Everything above runs in milliseconds with no fleet. The one chaos
test at the bottom (slow) is the ISSUE-15 acceptance: SIGKILL the
affinity-preferred worker mid-run — zero lost, greedy identity holds,
the dead worker's digest is invalidated, and the merged fleet
timeline validates clean.
"""

import types

import pytest

from ddp_practice_tpu.serve.affinity import (
    DIGEST_MAX_DEPTH,
    DigestPublisher,
    DigestView,
    AffinityPolicy,
    LeastLoadedPolicy,
    hash_extend,
    kv_summary,
    least_loaded_key,
    prompt_prefix_hashes,
    rendezvous_pick,
)
from ddp_practice_tpu.serve.health import HealthState

BS = 4  # block size for the host-pure tests: small trees, deep paths


# -------------------------------------------------------- hash namespace
def test_prompt_hashes_extend_blockwise():
    """out[d] names prompt[:(d+1)*bs]: each level extends the previous
    via hash_extend, and a one-token change at depth d perturbs every
    level >= d and none below."""
    prompt = list(range(1, 13))  # 3 full blocks
    hs = prompt_prefix_hashes(prompt, BS)
    assert len(hs) == 3
    h = prompt_prefix_hashes(prompt, BS)[0]
    assert hash_extend(h, prompt[BS:2 * BS]) == hs[1]
    other = list(prompt)
    other[BS] += 1  # first token of block 1
    hs2 = prompt_prefix_hashes(other, BS)
    assert hs2[0] == hs[0]
    assert hs2[1] != hs[1] and hs2[2] != hs[2]
    # partial trailing block contributes nothing; sub-block prompts none
    assert prompt_prefix_hashes(prompt + [99], BS) == hs
    assert prompt_prefix_hashes([1, 2], BS) == []
    assert prompt_prefix_hashes(prompt, 0) == []
    # depth cap bounds the walk
    assert len(prompt_prefix_hashes(list(range(64)), 1, max_depth=5)) == 5
    assert len(prompt_prefix_hashes(list(range(400)), 1)) \
        == DIGEST_MAX_DEPTH


def test_rendezvous_sticky_under_grow_and_shrink():
    """Membership churn moves ONLY the families that re-home onto (or
    off) the changed replica — everything else keeps its placement.
    This is the property that makes first-seen placement survive
    autoscaler grow/shrink without any shared ledger."""
    families = [hash_extend(0xABCDEF, (f,)) for f in range(200)]
    before = {f: rendezvous_pick(f, [0, 1, 2]) for f in families}
    assert set(before.values()) == {0, 1, 2}  # all replicas own some

    grown = {f: rendezvous_pick(f, [0, 1, 2, 3]) for f in families}
    moved = [f for f in families if grown[f] != before[f]]
    assert moved, "a new replica must claim some families"
    assert all(grown[f] == 3 for f in moved)

    shrunk = {f: rendezvous_pick(f, [0, 2]) for f in families}
    for f in families:
        if before[f] != 1:
            assert shrunk[f] == before[f]  # survivors keep theirs
        else:
            assert shrunk[f] in (0, 2)     # orphans re-home


# ------------------------------------------------------------- publisher
def _warm_radix(n_blocks=32, bs=BS):
    from ddp_practice_tpu.serve.kv_pages import (
        BlockAllocator,
        RadixPrefixCache,
    )

    alloc = BlockAllocator(n_blocks)
    return RadixPrefixCache(alloc, bs), alloc


def _insert(radix, alloc, tokens):
    n = len(tokens) // radix.block_size
    blocks = alloc.alloc(n)
    radix.insert(tokens, blocks)
    alloc.free(blocks)  # drop the caller ref: the tree's ref remains
    return tokens


def test_publisher_full_then_delta_then_resync_beat():
    radix, alloc = _warm_radix()
    fam_a = _insert(radix, alloc, list(range(8)))
    pub = DigestPublisher(radix, full_every=3)
    f1 = pub.frame()
    # first frame is always FULL, and its hashes are exactly the
    # prompt-side names for the cached path (the namespace contract)
    assert f1["v"] == 1 and f1["bs"] == BS
    assert sorted(prompt_prefix_hashes(fam_a, BS)) == f1["full"]
    # a second family arrives: the next frame is a DELTA from v1
    fam_b = _insert(radix, alloc, [70 + i for i in range(8)])
    f2 = pub.frame()
    assert f2["v"] == 2 and f2["base"] == 1 and f2["dels"] == []
    assert set(f2["adds"]) == set(prompt_prefix_hashes(fam_b, BS))
    # no tree edit -> version holds (re-emit is a freshness touch)
    assert pub.frame()["v"] == 2
    # the resync beat: every full_every-th call is full again
    f4 = pub.frame()
    assert "full" in f4 and sorted(f4["full"]) \
        == sorted(set(f1["full"]) | set(f2["adds"]))
    # eviction shows up as dels on the next frame
    assert radix.evict(2) == 2
    f5 = pub.frame()
    assert f5["v"] == 3 and f5["base"] == 2 and f5["dels"]


def test_publisher_depth_cap_mru_bound_and_epochs():
    radix, alloc = _warm_radix()
    old = _insert(radix, alloc, list(range(8)))        # 2 levels
    new = _insert(radix, alloc, [40 + i for i in range(8)])
    # depth cap: only the first-block names survive a max_depth=1 walk
    shallow = DigestPublisher(radix, max_depth=1).frame()
    assert set(shallow["full"]) == {
        prompt_prefix_hashes(old, BS)[0],
        prompt_prefix_hashes(new, BS)[0],
    }
    # MRU bound: with room for one entry, the LAST-touched path's
    # deepest node wins (hot families, not history)
    radix.match(new)  # touch
    tight = DigestPublisher(radix, max_entries=1).frame()
    assert tight["n"] == 1
    assert tight["full"][0] in prompt_prefix_hashes(new, BS)
    # two publisher incarnations never share an epoch (restart = new
    # tree = new namespace lifetime)
    assert DigestPublisher(radix).epoch != DigestPublisher(radix).epoch


# ------------------------------------------------------------------ view
def _full(hashes, v=1, epoch="e1", bs=BS):
    return {"v": v, "epoch": epoch, "bs": bs, "n": len(hashes),
            "full": sorted(hashes)}


def _delta(v, adds=(), dels=(), epoch="e1", bs=BS):
    return {"v": v, "epoch": epoch, "bs": bs, "n": 0,
            "base": v - 1, "adds": sorted(adds), "dels": sorted(dels)}


def test_view_apply_rules_and_decay():
    view = DigestView()
    assert not view.usable(0.0, 10.0)          # cold = unusable
    view.apply(_full([10, 20]), now=0.0)
    assert view.usable(0.0, 10.0) and view.hashes == {10, 20}
    # in-order delta applies
    view.apply(_delta(2, adds=[30], dels=[10]), now=1.0)
    assert view.hashes == {20, 30} and view.version == 2
    # same-version re-emit refreshes the clock, nothing else
    view.apply(_delta(2, adds=[30], dels=[10]), now=8.0)
    assert view.updated_at == 8.0 and view.hashes == {20, 30}
    # a SKIPPED delta (base 3 != version 2) = stale-until-full: the
    # view refuses to guess — stale costs a miss, never a wrong score
    view.apply(_delta(4, adds=[40]), now=9.0)
    assert view.stale and not view.usable(9.0, 10.0)
    view.apply(_full([40, 50], v=4), now=9.5)   # the resync beat lands
    assert view.usable(9.5, 10.0) and view.hashes == {40, 50}
    # freshness decays on the receiver's clock
    assert view.usable(19.5, 10.0)
    assert not view.usable(19.6, 10.0)
    # epoch change (worker restart) drops the dead tree's fingerprint
    view.apply(_delta(5, adds=[60], epoch="e2"), now=10.0)
    assert view.stale and view.hashes == set()
    view.apply(_full([60], v=5, epoch="e2"), now=10.5)
    assert view.usable(10.5, 10.0)
    # a None payload (digest vanished from the heartbeat) resets
    view.apply(None, now=11.0)
    assert not view.usable(11.0, 10.0)


def test_view_expected_hit_stops_at_first_gap():
    prompt = list(range(16))                    # 4 blocks
    hs = prompt_prefix_hashes(prompt, BS)
    view = DigestView()
    view.apply(_full([hs[0], hs[1], hs[3]]), now=0.0)  # hole at depth 2
    # prefix-closure: the walk stops at the gap even though a deeper
    # level is (spuriously) present
    assert view.expected_hit_tokens(hs) == 2 * BS
    assert view.expected_hit_tokens(prompt_prefix_hashes(
        [99] * 16, BS)) == 0


# ---------------------------------------------------------------- policy
def _cand(hid, load=0.0, state=HealthState.HEALTHY, kv=None):
    return types.SimpleNamespace(
        id=hid, load=load, health=types.SimpleNamespace(state=state),
        kv_summary=kv,
    )


def _kv(hashes, **kw):
    return {"block_size": BS, "digest": _full(hashes, **kw)}


def test_policy_fallback_is_byte_identical_without_digests():
    """No usable digest anywhere -> EXACTLY the least-loaded order, all
    decisions 'fallback', no expectations. Cache-awareness must cost
    nothing when it has nothing to say."""
    cands = [_cand(0, load=2.0), _cand(2, load=1.0),
             _cand(1, load=1.0, state=HealthState.DEGRADED)]
    pol = AffinityPolicy()
    ordered, decisions, exp = pol.order(cands, list(range(8)), now=0.0)
    want, want_d, want_e = LeastLoadedPolicy().order(
        cands, list(range(8)), now=0.0)
    assert [h.id for h in ordered] == [h.id for h in want] == [2, 0, 1]
    assert decisions == want_d == {0: "fallback", 2: "fallback",
                                   1: "fallback"}
    assert exp == want_e == {}
    assert least_loaded_key(cands[0]) < least_loaded_key(cands[2])


def test_policy_affinity_beats_load_when_warm():
    prompt = list(range(16))
    hs = prompt_prefix_hashes(prompt, BS)
    warm = _cand(1, load=1.0, kv=_kv(hs, epoch="w1"))
    cold = _cand(0, load=0.0, kv=_kv([777], epoch="w0"))
    pol = AffinityPolicy()  # load_penalty 32: 16 warm tokens > 1 load
    ordered, decisions, exp = pol.order([cold, warm], prompt, now=0.0)
    assert [h.id for h in ordered] == [1, 0]
    assert decisions == {1: "affinity", 0: "load"}
    assert exp == {1: 16, 0: 0}


def test_policy_load_wins_past_imbalance_cap():
    """A warm-but-swamped replica loses to the least-loaded order: the
    cap bounds how much queueing a hot family can buy."""
    prompt = list(range(16))
    hs = prompt_prefix_hashes(prompt, BS)
    warm = _cand(1, load=5.0, kv=_kv(hs, epoch="w1"))   # gap 5 > cap 4
    cold = _cand(0, load=0.0, kv=_kv([777], epoch="w0"))
    ordered, decisions, _ = AffinityPolicy().order(
        [cold, warm], prompt, now=0.0)
    assert [h.id for h in ordered] == [0, 1]
    assert decisions == {0: "load", 1: "load"}
    # ... but inside the cap, warmth still wins
    warm.load = 4.0
    ordered, decisions, _ = AffinityPolicy().order(
        [cold, warm], prompt, now=0.0)
    assert [h.id for h in ordered] == [1, 0]
    assert decisions[1] == "affinity"


def test_policy_first_seen_family_goes_to_rendezvous_home():
    """Digests warm, prompt unknown to all: the winner is the family's
    rendezvous home (so the cache warms where repeats will land), not
    simply the least-loaded replica."""
    prompt = list(range(16))
    home = rendezvous_pick(prompt_prefix_hashes(prompt, BS)[0], [0, 1])
    cands = [_cand(i, load=float(i == home), kv=_kv([777 + i]))
             for i in (0, 1)]  # bias load AGAINST the home replica
    ordered, decisions, exp = AffinityPolicy().order(
        cands, prompt, now=0.0)
    assert ordered[0].id == home
    assert decisions[home] == "affinity"
    assert exp == {0: 0, 1: 0}
    # a sub-block prompt has no family: nothing to be sticky about
    ordered, decisions, _ = AffinityPolicy().order(
        cands, [1, 2], now=0.0)
    assert [h.id for h in ordered] == [0, 1]   # plain least-loaded
    assert decisions == {0: "load", 1: "load"}


def test_policy_stale_digest_costs_a_miss_never_an_error():
    """A replica whose delta chain broke drops out of scoring (its
    requests fall back); the periodic full frame brings it back. The
    failure mode is a cache miss — never a misroute on stale truth."""
    prompt = list(range(16))
    hs = prompt_prefix_hashes(prompt, BS)
    pol = AffinityPolicy()
    a = _cand(0, load=0.0, kv=_kv(hs, epoch="a"))
    b = _cand(1, load=0.0, kv=_kv([777], epoch="b"))
    assert pol.order([a, b], prompt, 0.0)[1][0] == "affinity"
    # a's publisher moves on; the router misses frames v2..v4 and then
    # sees a delta it cannot apply -> view stale -> fallback order
    a.kv_summary = {"block_size": BS,
                    "digest": _delta(5, adds=[42], epoch="a")}
    b.kv_summary = None
    ordered, decisions, exp = pol.order([a, b], prompt, 1.0)
    assert decisions == {0: "fallback", 1: "fallback"}
    # the resync full frame restores scoring
    a.kv_summary = _kv(hs, v=5, epoch="a")
    assert pol.order([a, b], prompt, 2.0)[1][0] == "affinity"
    # forget() (kill/restart/retire) drops the view entirely
    pol.forget(0)
    assert 0 not in pol.views


def test_policy_decayed_digest_falls_back():
    prompt = list(range(16))
    hs = prompt_prefix_hashes(prompt, BS)
    pol = AffinityPolicy(max_age_s=10.0)
    a = _cand(0, kv=_kv(hs))
    assert pol.order([a], prompt, 0.0)[1][0] == "affinity"
    # heartbeats stop (digest still cached on the handle): the view
    # ages out on the router's clock and scoring declines to guess
    a.kv_summary = None
    assert pol.order([a], prompt, 11.0)[1][0] == "fallback"


# ------------------------------------------------- kv summary one-shape
def test_kv_summary_zeroes_for_slot_engines():
    """A slot engine (no paged pool, no radix) publishes honest zeroes
    and NO digest — the shape the router's fallback expects."""
    out = kv_summary(types.SimpleNamespace(blocks=None, radix=None))
    assert out["blocks_used"] == 0 and out["blocks_total"] == 0
    assert out["prefix_hit_rate"] == 0.0
    assert "digest" not in out and "block_size" not in out


def test_kv_summary_carries_digest_with_publisher():
    radix, alloc = _warm_radix()
    fam = _insert(radix, alloc, list(range(8)))
    eng = types.SimpleNamespace(blocks=alloc, radix=radix)
    out = kv_summary(eng, DigestPublisher(radix))
    assert out["block_size"] == BS
    assert sorted(out["digest"]["full"]) \
        == sorted(prompt_prefix_hashes(fam, BS))
    # blocks_total excludes the garbage block, matching the gauges
    assert out["blocks_total"] == alloc.num_blocks - 1


# ------------------------------------------- router: refused re-dispatch
class _FakeReplica:
    """The narrow ReplicaHandle interface, scripted: completions are
    injected by the test, submits recorded (or refused while
    'draining'), no engine anywhere."""

    def __init__(self, hid):
        self.id = hid
        self.submitted = []
        self.comps = []
        self.refuse = False
        self.last_submit_refused = False
        self.kv_summary = None
        self.has_queue_space = True
        self.max_slots = 4
        self.queue_len = 0
        self.active = 0

    def submit(self, req):
        if self.refuse:
            self.last_submit_refused = True
            return
        self.last_submit_refused = False
        self.submitted.append(req)

    def step(self):
        pass

    def poll(self):
        out, self.comps = self.comps, []
        return out

    def poll_chunks(self):
        return []

    def evacuate(self):
        return []

    def shed_queued(self, min_priority):
        return []

    @property
    def load(self):
        return float(len(self.submitted))

    def fits_prompt(self, n):
        return True

    def probe_ok(self, now):
        return True

    def restart(self):
        pass


def test_refused_completion_redispatches_without_penalty():
    """The one-way submit's reconcile path (supervisor): a worker that
    was draining answers the confirm poll with a refusal, which
    surfaces as a typed 'refused' completion. The router re-dispatches
    on the next candidate with NO breaker mark and NO retry charge —
    refusal is certain and typed, not a fault."""
    from ddp_practice_tpu.serve import FakeClock, Request, RouterConfig
    from ddp_practice_tpu.serve.router import Router
    from ddp_practice_tpu.serve.scheduler import Completion

    clock = FakeClock(step_s=0.01)
    h0, h1 = _FakeReplica(0), _FakeReplica(1)
    router = Router([h0, h1], clock=clock,
                    config=RouterConfig(retry_jitter=0.0))
    assert router.submit(Request(rid=7, prompt=[1, 2, 3],
                                 max_new_tokens=4))
    assert [r.rid for r in h0.submitted] == [7]  # least-loaded tie -> 0
    # worker 0 went draining AFTER the cast was sent: the reconcile
    # verdict comes back as a refusal, and the door stays shut
    h0.refuse = True
    h0.comps.append(Completion(
        rid=7, tokens=[], status="refused", arrival=0.0,
        finish=clock.now(), trace_id="r7",
    ))
    router.step()
    assert [r.rid for r in h1.submitted] == [7]  # re-homed, same rid
    # no penalty anywhere: healthy breaker, zero retries charged
    assert h0.health.state is HealthState.HEALTHY
    assert router.metrics.retries.value == 0
    # the re-dispatched attempt finishes normally
    h1.comps.append(Completion(
        rid=7, tokens=[9, 9, 9, 9], status="length", arrival=0.0,
        finish=clock.now(), trace_id="r7",
    ))
    (done,) = router.step()
    assert done.status == "length" and done.tokens == [9, 9, 9, 9]
    assert done.flight["retries"] == 0 and done.flight["failovers"] == 0
    assert done.flight["route"] == "fallback"
    assert done.flight["prefix_hit_tokens"] == 0


# ------------------------------------------------- chaos acceptance (slow)
@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_affinity_preferred_worker_failover_and_invalidate():
    """ISSUE-15 acceptance: 2 REAL paged worker processes, one shared
    prefix family homed by affinity, its preferred worker SIGKILLed
    mid-decode. Zero lost, greedy tokens identical to a fault-free
    single-replica run, the dead worker's digest view is invalidated
    (stale digest = a miss, and here not even that), and the merged
    fleet timeline validates clean."""
    import time

    import numpy as np

    from ddp_practice_tpu.serve.bench import build_shared_prefix_trace
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine
    from ddp_practice_tpu.serve.router import RouterConfig
    from ddp_practice_tpu.serve.scheduler import Request, Scheduler
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec, build_model
    from ddp_practice_tpu.utils.trace import TraceRecorder
    from tools.check_traces import validate, validate_fleet

    model_kw = {"vocab_size": 64, "max_len": 96, "hidden_dim": 64,
                "depth": 2, "num_heads": 4, "mlp_dim": 128,
                "pos_emb": "rope"}
    engine_kw = {"paged": True, "prefix_cache": True, "num_blocks": 48,
                 "block_size": 16, "max_slots": 2, "max_len": 96,
                 "prompt_buckets": [16, 32, 48, 64],
                 "temperature": 0.0, "decode_burst": 4, "eos_id": None}
    trace = build_shared_prefix_trace(
        n_requests=10, rate_hz=100.0, vocab=64, k_prefixes=1,
        prefix_len=32, tail_range=(1, 8), max_new_range=(5, 9), seed=9,
    )

    # fault-free greedy oracle: one in-process paged replica
    model, params = build_model(model_kw)
    eng_kw = dict(engine_kw)
    eng_kw.pop("paged")
    eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
    oracle = Scheduler(PagedEngine(model, params, EngineConfig(**eng_kw)),
                       max_queue=64)
    for t in trace:
        oracle.submit(Request(rid=t["rid"], prompt=t["prompt"],
                              max_new_tokens=t["max_new_tokens"]))
    expected = {c.rid: list(c.tokens)
                for c in oracle.run_until_idle()}
    assert all(expected.values())

    tracer = TraceRecorder()
    spec = WorkerSpec(model=model_kw, engine=engine_kw, max_queue=64,
                      trace=True)
    router, sup, handles = make_fleet_router(
        spec, 2, tracer=tracer, config=RouterConfig(cache_aware=True),
        sup_config=SupervisorConfig(restart_base_s=0.25,
                                    restart_budget=5,
                                    ready_timeout_s=300.0),
    )
    try:
        # warm round: the family's FIRST request lands on its
        # rendezvous home and warms that worker's radix tree
        warm = trace[:2]
        for t in warm:
            router.submit(Request(rid=t["rid"], prompt=t["prompt"],
                                  max_new_tokens=t["max_new_tokens"]))
        warm_comps = router.run_until_idle()
        assert all(c.status == "length" for c in warm_comps)
        from ddp_practice_tpu.serve.affinity import (
            prompt_prefix_hashes as pph,
            rendezvous_pick as rvp,
        )
        home = rvp(pph(trace[0]["prompt"], 16)[0], [0, 1])

        # wait for the home's heartbeat to carry a non-empty digest
        # (the policy applies it at the next dispatch); remember its
        # epoch so invalidation is observable after the kill
        def home_digest():
            kv = handles[home].kv_summary
            dg = (kv or {}).get("digest")
            return dg if dg and dg.get("n") else None

        deadline = time.monotonic() + 60
        while home_digest() is None:
            assert time.monotonic() < deadline, "digest never arrived"
            router.step()
            time.sleep(0.02)
        pre_epoch = home_digest()["epoch"]

        # mid-run: the rest of the family, then kill its home while it
        # is observably decoding
        rest = trace[2:]
        for t in rest:
            router.submit(Request(rid=t["rid"], prompt=t["prompt"],
                                  max_new_tokens=t["max_new_tokens"]))

        def home_busy():
            w = sup.worker(home)
            if w is None:
                return False
            try:
                st = w.client.call("ping", timeout_s=2.0)["stats"]
                return st["active"] > 0
            except Exception:
                return False

        deadline = time.monotonic() + 60
        while not home_busy():
            assert time.monotonic() < deadline, \
                "family traffic never reached its affinity home"
            router.step()
        victim_rids = sorted(handles[home].outstanding)
        assert victim_rids, "nothing in flight on the affinity home"
        sup.kill(home, "SIGKILL")
        comps = router.run_until_idle()

        # ---- zero lost, all terminal, greedy identity holds
        by_rid = {c.rid: c for c in comps}
        by_rid.update({c.rid: c for c in warm_comps})
        assert set(by_rid) == {t["rid"] for t in trace}
        assert all(c.status == "length" for c in by_rid.values())
        for rid, want in expected.items():
            assert list(by_rid[rid].tokens) == want, f"rid {rid} diverged"
        migrated = [rid for rid in victim_rids
                    if by_rid[rid].flight["failovers"] >= 1]
        assert migrated, "the kill migrated nothing"

        # ---- the dead home's digest was invalidated: either the view
        # is gone (_kill -> policy.forget) or it was rebuilt from the
        # RESPAWNED worker's new epoch — never the dead tree's
        view = router.policy.views.get(home)
        assert view is None or view.epoch != pre_epoch

        # ---- requests kept flowing: the survivor (and any respawn)
        # carried hit tokens; flights expose the routing decision
        routes = {c.flight.get("route") for c in by_rid.values()
                  if c.flight}
        assert routes <= {"affinity", "load", "fallback"}
        assert "affinity" in routes, "affinity never engaged"

        # ---- one validator-clean merged fleet timeline
        chrome = tracer.to_chrome_trace()
        assert validate(chrome) == []
        assert validate_fleet(chrome) == []
    finally:
        sup.stop()
