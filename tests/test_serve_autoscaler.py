"""serve/autoscaler.py — the elastic control loop, host-pure.

Three layers, no processes:

- **AutoscalerPolicy** with explicit timestamps: every transition the
  control law can make — eval throttle, trip-fast (pressure and SLO
  burn), deadband, resolve-slow calm window (anchor + reset), the
  no-reversal-inside-hold contract in BOTH directions, per-direction
  cooldowns, min/max clamps — replays on pinned FakeClock-style time.
  The anti-oscillation claim is pinned as a PROPERTY: an adversarial
  burst/calm square wave cannot extract more than elapsed/hold_s
  reversals, and consecutive reversals are >= hold_s apart.

- **StandbyPool** with `spawn_in_thread=False`: provision/take FIFO,
  replenish ordering, the spawn-error ledger, and close-reaps-all.

- **Autoscaler** over a FakeWorker supervisor and a REAL Router: warm
  promotion from the pool, cold fallback through the backoff pipeline
  (budget-free), scale-down via the drain path with retire-on-exit —
  including chaos SIGKILL mid-drain — plus snapshot/pressure_log/gauge
  plumbing. The real-process truth of the same loop is the slow+chaos
  test in tests/test_worker_fleet.py and the autoscale_burst_100rps
  bench.
"""

import pytest

from ddp_practice_tpu.serve.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    AutoscalerPolicy,
    StandbyPool,
)
from ddp_practice_tpu.serve.router import Router
from ddp_practice_tpu.serve.scheduler import FakeClock, Request
from ddp_practice_tpu.serve.supervisor import (
    BACKOFF,
    DRAINING,
    RUNNING,
    STOPPED,
    RemoteReplicaHandle,
    Supervisor,
    SupervisorConfig,
)
from ddp_practice_tpu.serve.worker import WorkerSpec


# --------------------------------------------------------------- policy
CFG = AutoscalerConfig(
    min_size=1, max_size=4, eval_interval_s=1.0,
    up_pressure=1.5, down_pressure=0.5,
    hold_s=10.0, cooldown_up_s=2.0, cooldown_down_s=15.0,
    down_stable_s=5.0, standby_target=1,
)


def test_config_validation():
    with pytest.raises(ValueError, match="min_size"):
        AutoscalerConfig(min_size=0)
    with pytest.raises(ValueError, match="max_size"):
        AutoscalerConfig(min_size=3, max_size=2)
    with pytest.raises(ValueError, match="deadband"):
        AutoscalerConfig(up_pressure=1.0, down_pressure=1.0)


def test_eval_throttle_one_evaluation_per_interval():
    pol = AutoscalerPolicy(CFG)
    assert pol.step(0.0, size=1, pressure=9.0) is not None
    # a raging burst 0.5s later is NOT evaluated — the throttle is on
    # evaluations, not just commits (cooldown_up_s alone would pass it)
    assert pol.step(0.5, size=1, pressure=9.0) is None
    assert pol._last_eval == 0.0


def test_trip_fast_on_queue_pressure_same_evaluation():
    pol = AutoscalerPolicy(CFG)
    d = pol.step(0.0, size=2, pressure=1.5)   # at threshold: inclusive
    assert d is not None
    assert d["direction"] == "up" and d["trigger"] == "queue_pressure"
    assert d["size"] == 2 and d["pressure"] == 1.5


def test_trip_fast_on_slo_burn_even_at_zero_pressure():
    # the burn alert means users are ALREADY hurting; pressure may lag
    pol = AutoscalerPolicy(CFG)
    d = pol.step(0.0, size=2, pressure=0.0, slo_active=True,
                 slo_resolved=False)
    assert d is not None and d["trigger"] == "slo_burn"


def test_deadband_moves_nothing():
    pol = AutoscalerPolicy(CFG)
    for k in range(20):
        assert pol.step(float(k), size=2, pressure=1.0) is None
    assert pol.events == [] and pol._calm_since is None


def test_resolve_slow_requires_continuous_calm():
    pol = AutoscalerPolicy(CFG)
    assert pol.step(0.0, size=2, pressure=0.1) is None   # calm anchors
    assert pol._calm_since == 0.0
    # one noisy sample inside the window resets the anchor entirely
    assert pol.step(2.0, size=2, pressure=1.0) is None
    assert pol._calm_since is None
    assert pol.step(3.0, size=2, pressure=0.1) is None   # re-anchor
    assert pol.step(7.0, size=2, pressure=0.1) is None   # 4s < 5s
    d = pol.step(8.0, size=2, pressure=0.1)              # 5s: resolve
    assert d is not None
    assert d["direction"] == "down" and d["trigger"] == "slo_resolved"


def test_calm_needs_slo_resolved_not_just_low_pressure():
    # a drained queue while the slow burn window still smolders is not
    # calm — scale-down waits for the watchdog's resolve
    pol = AutoscalerPolicy(CFG)
    for k in range(8):
        assert pol.step(float(k), size=2, pressure=0.0,
                        slo_resolved=False) is None
    assert pol._calm_since is None


def test_no_reversal_inside_hold_up_then_down():
    pol = AutoscalerPolicy(CFG)
    assert pol.step(0.0, size=1, pressure=3.0)["direction"] == "up"
    # burst ends instantly; calm holds its full 5s by t=6 — but the
    # up at t=0 forbids a down until t=10, however calm the fleet is
    for t in (1.0, 2.0, 6.0, 9.0):
        assert pol.step(t, size=2, pressure=0.0) is None
    d = pol.step(10.0, size=2, pressure=0.0)
    assert d is not None and d["direction"] == "down"


def test_no_reversal_inside_hold_down_then_up():
    pol = AutoscalerPolicy(CFG)
    for t in (0.0, 5.0):
        pol.step(t, size=3, pressure=0.0)
    assert pol.events[-1]["direction"] == "down"         # at t=5
    # the burst returns immediately: up is refused until t=15
    for t in (6.0, 10.0, 14.0):
        assert pol.step(t, size=2, pressure=9.0) is None
    d = pol.step(15.0, size=2, pressure=9.0)
    assert d is not None and d["direction"] == "up"


def test_per_direction_cooldowns_pace_same_direction_steps():
    pol = AutoscalerPolicy(CFG)
    assert pol.step(0.0, size=1, pressure=3.0) is not None
    assert pol.step(1.0, size=2, pressure=3.0) is None    # < 2s cooldown
    assert pol.step(2.0, size=2, pressure=3.0) is not None
    # downs pace on the LONG cooldown (resolve slow): first down at
    # t=20 (hold from the t=2 up expires at 12, calm anchored at 13)
    for t in (13.0, 20.0):
        pol.step(t, size=3, pressure=0.0)
    assert pol.events[-1] == dict(pol.events[-1], direction="down")
    down_t = pol.events[-1]["t"]
    assert down_t == 20.0
    # calm persists, but the next down waits out cooldown_down_s=15
    for t in (25.0, 30.0, 34.0):
        assert pol.step(t, size=2, pressure=0.0) is None
    assert pol.step(35.0, size=2, pressure=0.0) is not None


def test_min_max_clamp():
    pol = AutoscalerPolicy(CFG)
    assert pol.step(0.0, size=4, pressure=9.0) is None    # at max
    pol2 = AutoscalerPolicy(CFG)
    for t in (0.0, 6.0):
        assert pol2.step(t, size=1, pressure=0.0) is None  # at min
    assert pol2.events == []


def test_up_commit_reanchors_the_calm_window():
    # a grow is about to relieve pressure: inheriting pre-burst calm
    # samples would let a down fire moments after the up
    pol = AutoscalerPolicy(CFG)
    pol.step(0.0, size=2, pressure=0.1)
    assert pol._calm_since == 0.0
    pol.step(1.0, size=2, pressure=9.0)   # burst resets it anyway...
    pol.step(3.0, size=2, pressure=9.0)   # ...and the commit re-clears
    assert pol.events[-1]["direction"] == "up"
    assert pol._calm_since is None


def test_reversals_bounded_by_hold_window_property():
    """The anti-oscillation contract as a property: an adversarial
    burst/calm square wave (3.5s phases, shorter than hold_s) cannot
    extract reversals closer than hold_s apart, and no more than
    elapsed/hold_s + 1 of them, EVER."""
    cfg = AutoscalerConfig(
        min_size=1, max_size=4, eval_interval_s=0.5,
        up_pressure=1.5, down_pressure=0.5,
        hold_s=5.0, cooldown_up_s=0.5, cooldown_down_s=0.5,
        down_stable_s=0.5, standby_target=0,
    )
    pol = AutoscalerPolicy(cfg)
    size, t = 2, 0.0
    for k in range(400):
        burst = (k // 7) % 2 == 0          # 7 evals per phase = 3.5s
        d = pol.step(t, size=size,
                     pressure=(9.0 if burst else 0.0))
        if d is not None:
            size += 1 if d["direction"] == "up" else -1
            assert cfg.min_size <= size <= cfg.max_size
        t += 0.5
    evs = pol.events
    assert evs, "the adversary must provoke at least one event"
    reversals = [
        (a, b) for a, b in zip(evs, evs[1:])
        if a["direction"] != b["direction"]
    ]
    for a, b in reversals:
        assert b["t"] - a["t"] >= cfg.hold_s
    assert len(reversals) <= t / cfg.hold_s + 1


# ----------------------------------------------------------------- pool
class PoolWorker:
    def __init__(self, spec):
        self.spec = spec
        self.reaped = False

    def reap(self, timeout_s=5.0):
        self.reaped = True


def make_pool(fail_rids=()):
    spawned = []

    def spawn(spec):
        if spec.replica in fail_rids:
            raise RuntimeError(f"boom rid {spec.replica}")
        w = PoolWorker(spec)
        spawned.append(w)
        return w

    spec_fn = lambda rid: WorkerSpec(replica=rid)   # noqa: E731
    pool = StandbyPool(spec_fn, spawn_fn=spawn, spawn_in_thread=False)
    return pool, spawned


def test_pool_provision_take_fifo_and_ledgers():
    pool, spawned = make_pool()
    pool.provision(5)
    pool.provision(6)
    assert pool.ready_count == 2 and pool.in_flight == 0
    assert pool.spawned_total == 2 and len(spawned) == 2
    rid, spec, worker = pool.take()          # oldest first
    assert rid == 5 and spec.replica == 5 and worker is spawned[0]
    assert pool.take()[0] == 6
    assert pool.take() is None               # empty -> cold fallback
    assert pool.wait_ready(timeout_s=0.05) is False


def test_pool_spawn_error_ledger_does_not_wedge():
    pool, spawned = make_pool(fail_rids={7})
    pool.provision(7)
    pool.provision(8)
    assert pool.ready_count == 1
    assert pool.spawn_errors == [(7, "RuntimeError('boom rid 7')")]
    assert pool.take()[0] == 8               # the failure didn't block


def test_pool_close_reaps_and_refuses():
    pool, spawned = make_pool()
    pool.provision(1)
    pool.close()
    assert spawned[0].reaped
    pool.provision(2)                        # refused, not queued
    assert pool.ready_count == 0 and pool.in_flight == 0
    assert pool.take() is None
    assert pool.wait_ready(timeout_s=0.05) is False


# --------------------------------------------------------- orchestrator
class FakeClient:
    def __init__(self, handler):
        self.handler = handler
        self.calls = []
        self.closed = False

    def call(self, op, **fields):
        self.calls.append((op, fields))
        return {"ok": True, **self.handler(op, fields)}

    def close(self):
        self.closed = True


class ElasticWorker:
    """FakeWorker whose SIGTERM does NOT kill it — a draining worker
    survives until the test decides how it dies (clean, chaos SIGKILL,
    or the supervisor's deadline escalation)."""

    _next_pid = [6000]

    def __init__(self, spec, handler):
        ElasticWorker._next_pid[0] += 1
        self.pid = ElasticWorker._next_pid[0]
        self.spec = spec
        self.rc = None
        self.signals = []
        self.reaped = False
        self.telemetry_port = 9500 + self.pid % 100
        self.client = FakeClient(handler)

    def poll(self):
        return self.rc

    def kill_signal(self, sig):
        self.signals.append(sig)
        if sig == "SIGKILL":
            self.rc = -9

    def die(self, rc=1):
        self.rc = rc

    def reap(self, timeout_s=5.0):
        self.reaped = True
        self.client.close()


SPEC = WorkerSpec(engine={"max_slots": 2, "prompt_buckets": [8, 16]},
                  max_queue=8)
SUPCFG = SupervisorConfig(restart_base_s=0.2, restart_factor=2.0,
                          restart_max_s=10.0, restart_jitter=0.0,
                          restart_budget=3)


class FakeSLO:
    """Scriptable burn signal (the watchdog's own law is pinned in
    tests/test_slo.py — here it is an autoscaler INPUT)."""

    def __init__(self):
        self.active = False
        self.resolved = True

    def evaluate(self, now):
        pass

    def on_completion(self, c):
        pass

    def burn_signal(self):
        return {"burn_fast": 0.0, "burn_slow": 0.0,
                "active": self.active, "resolved": self.resolved}


def make_elastic(n=1, *, acfg=None, handler=None, slo=None):
    spawned = []

    def default_handler(op, fields):
        if op == "poll":
            return {"completions": [], "inflight": [], "watermark": 0,
                    "stats": {"queue": 0, "active": 0, "max_slots": 2}}
        return {"accepted": True}

    def spawn(spec):
        w = ElasticWorker(spec, handler or default_handler)
        spawned.append(w)
        return w

    clock = FakeClock(step_s=0.01)
    sup = Supervisor([SPEC] * n, SUPCFG, spawn_fn=spawn,
                     spawn_in_thread=False, clock=clock)
    sup.start()
    handles = [RemoteReplicaHandle(i, sup, SPEC, clock=clock)
               for i in range(n)]
    router = Router(handles, clock=clock, slo=slo)
    asc = Autoscaler(router, sup, SPEC,
                     config=acfg or AutoscalerConfig(
                         min_size=1, max_size=3, eval_interval_s=1.0,
                         up_pressure=1.5, down_pressure=0.5,
                         hold_s=10.0, cooldown_up_s=2.0,
                         cooldown_down_s=15.0, down_stable_s=5.0,
                         standby_target=1),
                     clock=clock, spawn_fn=spawn, spawn_in_thread=False)
    router.autoscaler = asc
    return router, sup, asc, clock, spawned


def _burst(router, clock, n=4, rid0=100):
    for i in range(n):
        assert router.submit(Request(rid=rid0 + i, prompt=[1, 2, 3],
                                     max_new_tokens=4,
                                     arrival=clock.now()))


def test_grow_promotes_warm_standby_and_joins_router():
    router, sup, asc, clock, spawned = make_elastic()
    assert asc.pool.ready_count == 1          # pre-provisioned, sync
    assert len(spawned) == 2                  # slot 0 + the standby
    _burst(router, clock)                     # load 4 / slots 2 = 2.0
    ev = asc.step(clock.now())
    assert ev is not None and ev["direction"] == "up"
    assert ev["trigger"] == "queue_pressure" and ev["warm"] is True
    assert ev["slot"] == 1 and ev["size"] == 2
    assert ev["join_s"] >= 0.0
    assert sup.state(1) == RUNNING            # promotion, not backoff
    assert sup.restarts[1] == 0 and sup._budget_used[1] == 0
    assert len(router.handles) == 2
    assert router.handles[-1].id == 1
    # the promoted worker was PROBED (ping) before dispatch trusts it
    assert ("ping" in [op for op, _ in spawned[1].client.calls])
    # pool replenished BEHIND the promotion, with a fresh rid
    assert asc.pool.ready_count == 1
    assert spawned[-1].spec.replica == 2
    # gauges track the event
    assert router.metrics.fleet_size.value == 2
    assert router.metrics.standby_ready.value == 1
    assert asc.snapshot()["events_total"] == 1


def test_grow_cold_fallback_when_pool_is_empty():
    acfg = AutoscalerConfig(min_size=1, max_size=3, eval_interval_s=1.0,
                            up_pressure=1.5, down_pressure=0.5,
                            hold_s=10.0, cooldown_up_s=2.0,
                            cooldown_down_s=15.0, down_stable_s=5.0,
                            standby_target=0)
    router, sup, asc, clock, spawned = make_elastic(acfg=acfg)
    assert asc.pool.ready_count == 0
    _burst(router, clock)
    ev = asc.step(clock.now())
    assert ev is not None and ev["warm"] is False
    # the cold slot rides the BACKOFF pipeline, due now, budget-free
    assert sup.state(1) == BACKOFF
    sup.poll()
    assert sup.state(1) == RUNNING
    assert sup.restarts[1] == 0 and sup._budget_used[1] == 0


def test_slo_burn_trips_scale_up_without_pressure():
    slo = FakeSLO()
    router, sup, asc, clock, spawned = make_elastic(slo=slo)
    slo.active, slo.resolved = True, False
    ev = asc.step(clock.now())
    assert ev is not None and ev["trigger"] == "slo_burn"
    assert sup.active_slots() == 2


def test_scale_down_drains_newest_and_retires_on_exit():
    router, sup, asc, clock, spawned = make_elastic(n=2)
    t0 = clock.now()
    assert asc.step(t0) is None               # calm anchors
    ev = asc.step(t0 + 6.0)                   # 6s calm > down_stable 5s
    assert ev is not None and ev["direction"] == "down"
    assert ev["slot"] == 1                    # newest leaves first
    # drain in flight: rpc drain + SIGTERM sent, handle stops offering
    w = spawned[1]
    assert ("drain", {"timeout_s": 1.0, "retries": 0}) in w.client.calls
    assert w.signals == ["SIGTERM"]
    assert sup.state(1) == DRAINING
    h1 = router.handles[-1]
    assert h1._drain_requested and not h1.has_queue_space
    assert len(router.handles) == 2           # still listed while alive
    assert asc.snapshot()["draining"] == [1]
    # the worker finishes its streams and exits CLEANLY
    w.die(rc=0)
    sup.poll()
    assert sup.state(1) == STOPPED
    asc.step(t0 + 7.0)                        # retire pass
    assert len(router.handles) == 1
    assert asc.drain_log[-1]["slot"] == 1
    assert asc.snapshot()["draining"] == []
    assert sup.restarts[1] == 0 and sup._budget_used[1] == 0
    assert router.metrics.fleet_size.value == 1


def test_chaos_sigkill_mid_drain_still_retires_without_budget():
    router, sup, asc, clock, spawned = make_elastic(n=2)
    t0 = clock.now()
    asc.step(t0)
    ev = asc.step(t0 + 6.0)
    assert ev is not None and ev["direction"] == "down"
    # chaos: SIGKILL the DRAINING worker mid-scale-down
    spawned[1].die(rc=-9)
    sup.poll()
    assert sup.state(1) == STOPPED            # retirement, not a crash
    asc.step(t0 + 7.0)
    assert len(router.handles) == 1
    assert sup.restarts[1] == 0 and sup._budget_used[1] == 0
    # and no respawn ever comes for the shrunk slot
    clock.advance(3600.0)
    sup.poll()
    assert sup.state(1) == STOPPED and len(spawned) == 3


def test_pressure_log_rows_once_per_evaluation():
    router, sup, asc, clock, spawned = make_elastic()
    t0 = clock.now()
    asc.step(t0)
    asc.step(t0 + 0.5)                        # throttled: no row
    asc.step(t0 + 1.0)
    assert [r["t"] for r in asc.pressure_log] == [t0, t0 + 1.0]
    assert all(r["size"] == 1 and r["pressure"] == 0.0
               for r in asc.pressure_log)


def test_router_step_ticks_the_loop_and_snapshot_shape():
    router, sup, asc, clock, spawned = make_elastic()
    _burst(router, clock)
    router.step()                             # router drives the tick
    assert asc.snapshot()["size"] == 2        # scaled up inside step()
    snap = asc.snapshot()
    assert set(snap) == {"size", "min", "max", "standby_ready",
                         "standby_target", "draining", "events_total",
                         "last_event", "last_join_s"}
    assert snap["min"] == 1 and snap["max"] == 3
    assert snap["last_event"]["direction"] == "up"
    assert snap["last_join_s"] is not None
    asc.close()
    assert asc.pool.ready_count == 0
