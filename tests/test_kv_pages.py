"""Paged KV cache: block allocator determinism + paged-engine invariants.

The host-side allocator / refcount / radix-tree tests are jit-free and
run in the tier-1 gate; everything that compiles an engine is marked
`slow` (each costs a prefill+decode compile pair, ~15-25 s on the CI
CPU). The paged-vs-slot and prefix-vs-plain greedy equivalences on
shared traces live with the other equivalence pins in
tests/test_serve_equivalence.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, PagedEngine
from ddp_practice_tpu.serve.kv_pages import (
    GARBAGE_BLOCK,
    BlockAllocator,
    RadixPrefixCache,
)
from ddp_practice_tpu.serve.scheduler import FakeClock, Request, Scheduler

VOCAB = 32


def _tolerate_load_flake(attempt, tries=2):
    """One retry for cross-IMPLEMENTATION greedy-identity pins (preempted
    vs uncontended pool, forked/CoW vs solo engine): this image's XLA CPU
    is not bitwise run-to-run deterministic under load, so a near-tied
    argmax over the toy model can flip one late token between process
    runs. Same contract as tests/test_serve_equivalence.py — a real
    divergence bug fails every attempt."""
    for i in range(tries):
        try:
            return attempt()
        except AssertionError:
            if i == tries - 1:
                raise


# ------------------------------------------------------------- host-only
def test_allocator_is_deterministic_and_reuses_freed_blocks():
    a = BlockAllocator(8)  # blocks 1..7 allocatable; 0 is the garbage block
    first = a.alloc(3)
    assert first == [1, 2, 3]
    second = a.alloc(2)
    assert second == [4, 5]
    a.free(first)
    # freed blocks go to the BACK: older free blocks hand out first,
    # then the released ones in release order
    assert a.alloc(4) == [6, 7, 1, 2]
    assert a.num_used == 6 and a.num_free == 1


def test_allocator_exhaustion_returns_none_without_side_effects():
    a = BlockAllocator(4)
    assert a.alloc(5) is None          # all-or-nothing: nothing consumed
    assert a.num_free == 3
    got = a.alloc(3)
    assert got == [1, 2, 3]
    assert a.alloc(1) is None
    a.free([2])
    assert a.alloc(1) == [2]


def test_allocator_rejects_bad_frees_and_sizes():
    a = BlockAllocator(4)
    with pytest.raises(ValueError):
        a.free([1])                    # never allocated
    with pytest.raises(ValueError):
        a.alloc(-1)
    with pytest.raises(ValueError):
        BlockAllocator(1)              # garbage block only — no pool
    assert a.alloc(0) == []


def test_refcounted_blocks_free_only_at_last_holder():
    """A shared block survives any one holder's release: free() is a
    deref, the free list sees the block only at refcount zero."""
    a = BlockAllocator(4)
    (b,) = a.alloc(1)
    a.ref([b])                     # second holder (prefix cache / fork)
    assert a.refcount(b) == 2 and a.num_shared == 1
    a.free([b])                    # first holder lets go
    assert a.refcount(b) == 1 and a.num_used == 1 and a.num_shared == 0
    assert b not in (a.alloc(2) or [])   # still not reallocatable
    a.free([b])                    # last holder
    assert a.refcount(b) == 0
    assert a.alloc(1) == [b]       # now it cycles back (tail of the list)
    with pytest.raises(ValueError):
        a.ref([99])                # never allocated


def test_garbage_block_is_outside_the_refcount_economy():
    """Block-0 guard (the retired-slot DMA target): the allocator never
    hands it out, and refcounting or freeing it is a loud error — a
    shared block aliasing the garbage-DMA target would let retired
    slots scribble over live prefixes."""
    a = BlockAllocator(4)
    got = a.alloc(3)
    assert GARBAGE_BLOCK not in got and a.num_free == 0
    with pytest.raises(ValueError, match="garbage"):
        a.ref([GARBAGE_BLOCK])
    with pytest.raises(ValueError, match="garbage"):
        a.free([GARBAGE_BLOCK])
    radix = RadixPrefixCache(BlockAllocator(4), 4)
    with pytest.raises(ValueError, match="garbage"):
        radix.insert(list(range(4)), [GARBAGE_BLOCK])


def test_radix_match_insert_and_block_granularity():
    """Block-granular prefix matching: only full cached blocks match,
    and a full-prompt match always leaves >= 1 token to prefill (the
    admission needs the last prompt token's logits)."""
    a = BlockAllocator(16)
    r = RadixPrefixCache(a, 4)
    p1 = [1, 2, 3, 4, 5, 6, 7, 8, 9]          # 2 full blocks + 1 token
    blocks = a.alloc(2)
    r.insert(p1, blocks)
    assert len(r) == 2
    assert a.refcount(blocks[0]) == 2          # owner + tree
    # same first block, diverging second
    got, matched = r.match([1, 2, 3, 4, 9, 9, 9, 9, 1])
    assert matched == 4 and got == [blocks[0]]
    assert a.refcount(blocks[0]) == 3          # match refs for the caller
    a.free(got)
    # exact full-block prompt: the trailing matched block is DROPPED so
    # one token remains to prefill
    got, matched = r.match([1, 2, 3, 4, 5, 6, 7, 8])
    assert matched == 4 and got == [blocks[0]]
    a.free(got)
    # re-inserting an already-cached chunk keeps the EXISTING node
    dup = a.alloc(2)
    assert r.insert(p1, dup) == 0 and len(r) == 2
    assert a.refcount(dup[0]) == 1             # duplicate stays private


def test_radix_eviction_is_lru_leaf_first_and_never_referenced():
    """evict() frees LRU leaves whose block only the tree holds;
    blocks a slot still references are untouchable — the
    evict-while-referenced impossibility, host-pure."""
    a = BlockAllocator(16)
    r = RadixPrefixCache(a, 2)
    ba = a.alloc(2)                            # slot-held [1, 2]
    bb = a.alloc(2)                            # slot-held [3, 4]
    r.insert([1, 2, 3, 4], ba)                 # chain A1 -> A2
    r.insert([1, 2, 9, 9], [ba[0], bb[1]])     # sibling S under A1
    r.insert([5, 6], bb[:1])                   # lone leaf B
    assert len(r) == 4                         # A1, A2, S, B
    got, _ = r.match([1, 2, 9, 9, 0])          # touch A1 + S (recent)
    a.free(got)                                # drop the match refs
    # every block still has a slot holder; nothing is evictable
    assert r.evictable() == 0 and r.evict(8) == 0
    # release the slot refs of A2 and B: both become evictable leaves;
    # A2 (inserted first, never matched) is the LRU victim
    a.free([ba[1], bb[0]])
    assert r.evictable() == 2
    assert r.evict(1) == 1
    assert a.refcount(ba[1]) == 0          # A2 went first (LRU)
    assert a.refcount(bb[0]) == 1          # B survived this round
    # drop the remaining slot refs: the whole tree drains leaf-first
    # (evicting S exposes A1 as a new leaf)
    a.free([ba[0]])
    a.free([bb[1]])
    assert r.evict(8) == 3 and len(r) == 0
    assert a.num_used == 0


def test_radix_counts_hit_and_miss_tokens():
    a = BlockAllocator(8)
    r = RadixPrefixCache(a, 4)
    blocks = a.alloc(1)
    r.insert([1, 2, 3, 4, 5], blocks)
    got, m = r.match([1, 2, 3, 4, 7, 7])
    a.free(got)
    assert (r.hit_tokens, r.miss_tokens) == (4, 2)
    got, m = r.match([9, 9])
    assert (r.hit_tokens, r.miss_tokens) == (4, 4)


def test_ref_prefix_pins_the_chain_against_eviction():
    """make_room regression: `ref_prefix` pins the blocked request's own
    cached chain so a targeted eviction pass can never consume the very
    blocks that made the request servable — and the pin is a pure probe
    (no hit/miss accounting, no LRU stamp, drops cleanly)."""
    a = BlockAllocator(16)
    r = RadixPrefixCache(a, 2)
    ba = a.alloc(3)
    r.insert([1, 2, 3, 4, 5, 6], ba)       # chain A (older insert)
    bb = a.alloc(1)
    r.insert([8, 8], bb)                   # unrelated leaf B (younger)
    a.free(ba)
    a.free(bb)                             # tree-only: all eviction fodder
    hits = (r.hit_tokens, r.miss_tokens)
    # whole-prompt pin clamps like match: >=1 token left to prefill
    assert r.ref_prefix([1, 2]) == []
    pinned = r.ref_prefix([1, 2, 3, 4, 5, 6, 9])
    assert pinned == ba                    # the full chain
    assert (r.hit_tokens, r.miss_tokens) == hits   # gate-probe pure
    # the pinned chain is untouchable: a blanket evict only takes B
    assert r.evict(8) == 1
    assert a.refcount(bb[0]) == 0
    assert all(a.refcount(b) == 2 for b in ba)     # tree ref + pin
    a.free(pinned)                         # drop the pins
    assert r.evict(8) == 3 and len(r) == 0
    assert a.num_used == 0
    # and the pin never stamped LRU: rebuild both, pin-and-drop A, the
    # chain tail (older insert) is still the first victim — a stamping
    # ref_prefix would have promoted A past B
    ba = a.alloc(3)
    r.insert([1, 2, 3, 4, 5, 6], ba)
    bb = a.alloc(1)
    r.insert([8, 8], bb)
    a.free(ba)
    a.free(bb)
    a.free(r.ref_prefix([1, 2, 3, 4, 5, 6, 9]))
    assert r.evict(1) == 1
    assert a.refcount(ba[2]) == 0          # A's tail went (LRU intact)
    assert a.refcount(bb[0]) == 1          # B survived


def test_evictable_counter_matches_full_walk_on_random_ops():
    """The O(1) evictable counter (insert/evict structural edges +
    allocator refcount hook) must agree with the full-tree walk after
    EVERY operation of a randomized admit/release/evict/pin history —
    the admit-gate probe reads the counter, so a drifting counter would
    silently admit into blocks that cannot actually be freed."""
    rng = np.random.default_rng(7)
    a = BlockAllocator(64)
    r = RadixPrefixCache(a, 2)
    held = []      # (blocks, tokens) a live "slot" still references
    for step in range(400):
        op = int(rng.integers(0, 4))
        if op == 0:
            # an admission: match the cached prefix, alloc own blocks,
            # publish the prompt (duplicate chunks stay private)
            plen = int(rng.integers(1, 11))
            tokens = [int(t) for t in rng.integers(0, 4, plen)]
            blocks = a.alloc(-(-plen // r.block_size))
            if blocks is not None:
                got, _ = r.match(tokens)
                r.insert(tokens, blocks)
                held.append((blocks + got, tokens))
        elif op == 1 and held:
            # a release: the slot drops every block it held
            blocks, _ = held.pop(int(rng.integers(0, len(held))))
            a.free(blocks)
        elif op == 2:
            r.evict(int(rng.integers(1, 5)))
        elif op == 3 and held:
            # a make_room-style pin/unpin cycle
            pins = r.ref_prefix(
                held[int(rng.integers(0, len(held)))][1]
            )
            a.free(pins)
        assert r.evictable() == r._evictable_walk(), f"drift at {step}"
    for blocks, _ in held:
        a.free(blocks)
    assert r.evictable() == r._evictable_walk()
    r.clear()
    assert r.evictable() == r._evictable_walk() == 0


# ------------------------------------------------------- engine (compiles)
@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=32, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _paged(lm, **kw):
    model, params = lm
    kw.setdefault("max_slots", 2)
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("block_size", 8)
    kw.setdefault("max_blocks_per_slot", 4)
    return PagedEngine(model, params, EngineConfig(**kw))


@pytest.mark.slow
def test_freed_block_contents_never_visible_to_new_occupant(lm, devices):
    """A released request's K/V stays in its blocks; the next occupant of
    those blocks must decode exactly its solo tokens — masking to the
    slot's own written positions is what makes reuse safe."""
    # 2 real blocks total: B can only run inside A's released pages
    eng = _paged(lm, max_slots=1, max_blocks_per_slot=2, num_blocks=3)
    pa, pb = [3, 1, 4, 1, 5], [2, 7, 1]

    sa = eng.admit(pa, max_positions=8)
    for _ in range(8):
        eng.step()
    blocks_a = [int(b) for b in eng._pt[sa, : int(eng._nblk[sa])]]
    eng.release(sa)

    sb = eng.admit(pb, max_positions=8)
    got = [int(eng.step()[sb]) for _ in range(8)]
    blocks_b = [int(b) for b in eng._pt[sb, : int(eng._nblk[sb])]]

    solo = _paged(lm, max_slots=1, max_blocks_per_slot=2, num_blocks=3)
    ss = solo.admit(pb, max_positions=8)
    want = [int(solo.step()[ss]) for _ in range(8)]
    assert got == want
    # the reuse actually happened: B decoded inside A's old pages
    assert set(blocks_b) == set(blocks_a)


@pytest.mark.slow
def test_page_tables_grow_across_block_boundaries(lm, devices):
    """Decode crossing a block boundary allocates lazily (no up-front
    reservation since PR 6); the page-table row and allocator agree at
    every step, and growth past the admit-time max_positions BUDGET
    refuses loudly without leaking blocks."""
    eng = _paged(lm, max_slots=2, block_size=8, max_blocks_per_slot=4)
    s = eng.admit([1, 2, 3], max_positions=16)   # bucket 8 -> 1 block now
    assert int(eng._nblk[s]) == 1
    assert int(eng._budget[s]) == 3              # ceil((8+16)/8) cap
    for i in range(16):
        eng.step()
    # context 8+16=24 -> 3 blocks, lazily grown to the budget
    assert eng.context_len(s) == 24
    assert int(eng._nblk[s]) == 3
    rows = [int(b) for b in eng._pt[s, :3]]
    assert len(set(rows)) == 3 and GARBAGE_BLOCK not in rows
    # stepping past the admit-time budget refuses loudly BEFORE
    # touching the allocator (no leaked blocks)
    free_before = eng.blocks.num_free
    with pytest.raises(RuntimeError, match="budget"):
        eng.step()
    assert eng.blocks.num_free == free_before
    used_before = eng.blocks.num_used
    eng.release(s)
    assert eng.blocks.num_used == used_before - 3


@pytest.mark.slow
def test_block_exhaustion_preempts_and_readmits(lm, devices):
    """Block-aware preemption replaces the PR-3 worst-case reservation:
    a pool that cannot hold every admitted request's full context any
    more EVICTS the youngest-admitted slot mid-decode (its request is
    re-queued and re-prefilled by the scheduler), instead of refusing
    the admissions up front — and the final greedy tokens are identical
    to an uncontended pool's."""
    from ddp_practice_tpu.serve.metrics import ServeMetrics

    def run(num_blocks):
        eng = _paged(lm, max_slots=4, block_size=8, max_blocks_per_slot=3,
                     num_blocks=num_blocks)
        metrics = ServeMetrics()
        sched = Scheduler(eng, clock=FakeClock(), metrics=metrics)
        for rid in range(3):          # each needs 3 blocks eventually
            assert sched.submit(Request(rid=rid, prompt=[1 + rid],
                                        max_new_tokens=16))
        done = sched.run_until_idle()
        return eng, metrics, {c.rid: (c.status, c.tokens) for c in done}

    def attempt():
        # 6 real blocks < 3 requests x 3 blocks: must preempt to finish
        eng, metrics, got = run(num_blocks=7)
        assert eng.preemptions > 0
        assert all(s == "length" and len(t) == 16 for s, t in got.values())
        assert eng.blocks.num_used == 0
        assert metrics.preemptions.value == eng.preemptions
        assert metrics.blocks_free.value == eng.blocks_available == 6
        assert metrics.block_occupancy.value == 0.0
        # an uncontended pool (full backing) produces the same tokens
        eng2, _, want = run(num_blocks=0)
        assert eng2.preemptions == 0
        assert got == want
        # "never" still guards what preemption can NOT fix: one request
        # outgrowing the per-slot capacity or the whole pool
        assert eng.admit_gate(3, 100) == "never"

    _tolerate_load_flake(attempt)


@pytest.mark.slow
def test_long_context_outgrows_model_max_len(lm, devices, compile_guard):
    """The paged headline: a request keeps decoding past the model's
    max_len (slot-engine hard ceiling) as long as blocks exist — RoPE
    positions are unbounded and the span is the slot's own pages."""
    model, _ = lm
    eng = _paged(lm, block_size=8, max_blocks_per_slot=6)  # cap 48 > 32
    assert eng.max_context > model.max_len
    s = eng.admit([3, 1, 4, 1, 5])
    toks = [int(eng.step()[s]) for _ in range(4)]
    with compile_guard(eng):                      # growth never recompiles
        for _ in range(36):
            toks.append(int(eng.step()[s]))
    assert eng.context_len(s) == 48 > model.max_len
    assert all(0 <= t < VOCAB for t in toks)


@pytest.mark.slow
def test_churn_is_compile_free_after_warmup(lm, devices, compile_guard):
    """Two programs per bucket set, pinned via the conftest helper:
    arbitrary admit/step/release churn after warmup compiles nothing.
    The PR-6 counters (prefix prefill / CoW) sit at zero for a plain
    engine — those paths never run without the prefix cache."""
    eng = _paged(lm)
    slot = eng.admit([1, 2, 3], max_positions=8)
    eng.step()
    eng.release(slot)
    assert eng.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
        "prefix_prefill_compiles": 0, "cow_compiles": 0,
    }
    rng = np.random.default_rng(7)
    with compile_guard(eng):
        for _ in range(5):
            n = int(rng.integers(1, 9))
            s = eng.admit(rng.integers(0, VOCAB, n).tolist(),
                          max_positions=8)
            for _ in range(int(rng.integers(1, 8))):
                eng.step()
            eng.release(s)


@pytest.mark.slow
def test_prefix_hit_skips_prefill_and_shares_blocks(lm, devices,
                                                    compile_guard):
    """The tentpole observable: a second admission of a shared prompt
    matches the radix cache, attaches the cached blocks refcounted,
    prefills only the suffix — and churn on every new path (prefix hit,
    CoW split, preempt) stays compile-free after warmup."""
    eng = _paged(lm, max_slots=3, prompt_buckets=(8, 16),
                 max_blocks_per_slot=4, prefix_cache=True)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]    # 11 tokens, 1 full block
    sa = eng.admit(prompt, max_positions=8)
    first = [int(eng.step()[sa]) for _ in range(8)]
    eng.release(sa)
    assert len(eng.radix) == 1                     # positions [0, 8) cached
    assert eng.blocks.num_used >= 1                # survives the release
    # CoW warm-up: fork splits the shared tail once so the program exists
    sw = eng.admit(prompt, max_positions=8)
    fw = eng.fork(sw, seed=1)
    eng.step()
    eng.release(sw)
    eng.release(fw)
    hit0 = eng.radix.hit_tokens
    stats = eng.compile_stats()
    assert stats["prefix_prefill_compiles"] >= 1
    assert stats["cow_compiles"] == 1
    with compile_guard(eng):
        sb = eng.admit(prompt, max_positions=8)    # HIT: 8 cached tokens
        assert eng.radix.hit_tokens == hit0 + 8
        assert eng.blocks.refcount(int(eng._pt[sb, 0])) >= 2  # shared
        again = [int(eng.step()[sb]) for _ in range(8)]
        sc = eng.fork(sb, seed=2)                  # CoW split re-runs
        eng.step()
        eng.release(sb)
        eng.release(sc)
    assert all(0 <= t < VOCAB for t in again)
    assert all(0 <= t < VOCAB for t in first)


@pytest.mark.slow
def test_fork_cow_never_leaks_and_freed_shared_contents_stay_invisible(
        lm, devices):
    """Refcount/CoW invariants through the device path: siblings share
    blocks until one writes (CoW splits, the other's context is
    untouched), releasing the parent mid-flight leaves the child's
    tokens exactly its solo continuation, and a freed shared block's
    contents are never visible to the next occupant."""
    def attempt():
        eng = _paged(lm, max_slots=3, prompt_buckets=(8,),
                     max_blocks_per_slot=3, prefix_cache=True)
        prompt = [2, 7, 1, 8, 2, 8]
        sa = eng.admit(prompt, max_positions=16)
        warm = [int(eng.step()[sa]) for _ in range(3)]
        child = eng.fork(sa, seed=0)
        assert eng.blocks.num_shared >= 1
        # release the PARENT immediately: every shared block must survive
        # for the child (free is a deref, not a reclaim)
        eng.release(sa)
        got = [int(eng.step()[child]) for _ in range(5)]
        eng.release(child)
        assert eng.blocks.num_shared == 0
        # solo oracle: the same prompt run without fork/release churn
        solo = PagedEngine(*lm, EngineConfig(
            max_slots=3, prompt_buckets=(8,), block_size=8,
            max_blocks_per_slot=3, prefix_cache=True,
        ))
        ss = solo.admit(prompt, max_positions=16)
        want = [int(solo.step()[ss]) for _ in range(8)]
        assert warm + got == want
        # pool fully drains once the tree is cleared (no leaked refs)
        eng.radix.clear()
        assert eng.blocks.num_used == 0

    _tolerate_load_flake(attempt)


@pytest.mark.slow
def test_retired_slot_garbage_dma_never_aliases_shared_blocks(
        lm, devices):
    """Block-0 regression: a retired slot's page-table row points at the
    garbage block, and with prefix sharing in play the garbage block
    must never BE a shared block — decode bursts after a release keep
    scribbling into block 0, and a cached prefix living there would be
    silently corrupted for every later hit."""
    eng = _paged(lm, max_slots=2, prompt_buckets=(8, 16),
                 max_blocks_per_slot=3, prefix_cache=True)
    prompt = [4, 2, 4, 2, 4, 2, 4, 2, 6]          # one full block + 1
    sa = eng.admit(prompt, max_positions=8)
    la = np.asarray(eng._last_logits[sa], np.float32).copy()
    sb = eng.admit([9, 9, 9], max_positions=8)    # keeps the batch busy
    for _ in range(4):
        eng.step()
    eng.release(sa)                                # row -> garbage block
    assert all(int(b) == GARBAGE_BLOCK for b in eng._pt[sa])
    # cached prefix blocks are refcounted, never block 0
    assert len(eng.radix) >= 1
    for node in eng.radix._iter_nodes():
        assert node.block != GARBAGE_BLOCK
        assert eng.blocks.refcount(node.block) >= 1
    # burst on: the retired row's garbage DMA scribbles every step
    for _ in range(4):
        eng.step()
    # a fresh HIT on the cached prefix sees the SAME next-token logits
    # as the original occupant (to float noise) — the garbage writes
    # landed in block 0, not in the shared prefix pages
    hit0 = eng.radix.hit_tokens
    sc = eng.admit(prompt, max_positions=8)
    assert eng.radix.hit_tokens == hit0 + 8       # it really hit
    lc = np.asarray(eng._last_logits[sc], np.float32)
    np.testing.assert_allclose(lc, la, atol=1e-3, rtol=1e-3)


@pytest.mark.slow
def test_make_room_spares_the_blocked_requests_own_prefix(lm, devices):
    """make_room regression: a blocked LONG prompt that is only servable
    BECAUSE its prefix is warm (suffix fits a bucket, whole prompt does
    not) must not have that prefix consumed by its own make_room pass —
    the old blanket evict flipped a feasible "later" into "never"."""
    eng = _paged(lm, max_slots=3, prompt_buckets=(8,), block_size=4,
                 max_blocks_per_slot=5, num_blocks=8, prefix_cache=True)
    warm = [3, 1, 4, 1, 5, 9, 2, 6]               # 8 tokens = 2 full blocks
    long_prompt = warm + [5, 3, 5, 8, 9, 7, 9, 3]  # 16 > largest bucket
    s0 = eng.admit(warm, max_positions=4)
    eng.release(s0)                               # chain tree-only (rc1)
    assert eng.radix.peek(long_prompt) == 8
    # cold, the long prompt outgrows every bucket; warm, it is servable
    assert eng.admit_gate(16, 4) == "never"
    assert eng.admit_gate(16, 4, prompt=long_prompt) != "never"
    # crowd the pool with runners (2 table blocks each, tree-shared):
    # 7 real blocks = 2 (warm chain) + 2 + 2, one on the free list
    sa = eng.admit([7, 7, 2, 2, 4, 4, 6, 6], max_positions=4)
    sb = eng.admit([11, 12, 13, 14], max_positions=4)
    assert eng.blocks.num_free == 1
    assert eng.admit_gate(16, 4, prompt=long_prompt) == "later"
    # the targeted pass pins the head's own chain: nothing else is
    # evictable, so it frees nothing — and must NOT eat the prefix
    assert not eng.make_room(16, 4, prompt=long_prompt)
    assert eng.radix.peek(long_prompt) == 8        # prefix survived
    assert eng.radix.evictable() == 1              # pins dropped (rc back)
    assert eng.admit_gate(16, 4, prompt=long_prompt) == "later"  # not never
    # "later" was honest: one release frees the shortfall and the long
    # prompt admits THROUGH its warm prefix
    eng.release(sa)
    assert eng.admit_gate(16, 4, prompt=long_prompt) == "ok"
    hit0 = eng.radix.hit_tokens
    sc = eng.admit(long_prompt, max_positions=4)
    assert eng.radix.hit_tokens == hit0 + 8
    eng.release(sb)
    eng.release(sc)


@pytest.mark.slow
def test_make_room_drains_deep_chains_through_exposure(lm, devices):
    """Targeted make_room passes the FULL shortfall to evict(): a deep
    single-leaf chain (evictable()==1) still covers a multi-block need
    through the leaf-exposure loop, instead of freeing one block and
    leaking the rest of the pressure into runner preemption."""
    eng = _paged(lm, max_slots=2, prompt_buckets=(8,), block_size=4,
                 max_blocks_per_slot=5, num_blocks=8, prefix_cache=True)
    chain = eng.blocks.alloc(3)
    eng.radix.insert(list(range(12)), chain)       # 12 tokens = 3 blocks
    eng.blocks.free(chain)                         # tree-only deep chain
    held = eng.blocks.alloc(4)                     # the rest of the pool
    assert eng.blocks.num_free == 0
    assert eng.radix.evictable() == 1              # one leaf, 3 blocks deep
    prompt = [20, 21, 22, 23, 24, 25, 26, 27]      # no cached prefix
    assert eng.admit_gate(8, 4, prompt=prompt) == "later"
    assert eng.make_room(8, 4, prompt=prompt)      # all 3 via exposure
    assert eng.blocks.num_free == 3
    assert eng.admit_gate(8, 4, prompt=prompt) == "ok"
    eng.blocks.free(held)


# ------------------------------------------------- replayable fork seeds
@pytest.mark.slow
def test_fork_seed_chains_diverge_and_replay_across_layouts(lm, devices):
    """Child PRNG chains are a pure function of (request seed, fork
    ordinal): siblings DIVERGE by construction, and a replay whose
    allocator hands out entirely different slot ids reproduces each
    sibling's exact sampled stream — the property n>1 sampling needs
    for deterministic trace replay. Explicit seed= starts a fresh
    chain: two forks pinned to the same seed emit the same tokens."""
    prompt = [3, 1, 4, 1, 5]

    def _run(layout_admits):
        eng = _paged(lm, max_slots=4, temperature=0.8, top_k=8,
                     num_blocks=24)
        # perturb the slot layout: transient admits shift which slot
        # ids the parent and children land on between replays
        dummies = [eng.admit([9, 8, 7], max_positions=8)
                   for _ in range(layout_admits)]
        s = eng.admit(prompt, max_positions=16, seed=42)
        for d in dummies:
            eng.release(d)
        eng.step()                      # pre-fork decode history
        c1 = eng.fork(s)
        c2 = eng.fork(s)
        slots = {"parent": s, "c1": c1, "c2": c2}
        out = {k: [] for k in slots}
        for _ in range(5):
            toks = eng.step()
            for k, slot in slots.items():
                out[k].append(int(toks[slot]))
        for slot in (c1, c2):
            eng.release(slot)
        e1 = eng.fork(s, seed=7)
        e2 = eng.fork(s, seed=7)
        toks = eng.step()
        out["explicit"] = (int(toks[e1]), int(toks[e2]))
        return slots, out

    def attempt():
        slots_a, a = _run(0)
        slots_b, b = _run(2)
        assert slots_a != slots_b       # the layouts really differed
        # divergence: three distinct streams from one admitted request
        assert len({tuple(a[k]) for k in ("parent", "c1", "c2")}) == 3
        # replay determinism: per-sibling streams survive the re-layout
        assert a == b
        # explicit same seed = same fresh chain = same draw
        assert a["explicit"][0] == a["explicit"][1]

    _tolerate_load_flake(attempt)
