"""Paged KV cache: block allocator determinism + paged-engine invariants.

The host-side allocator tests are jit-free and run in the tier-1 gate;
everything that compiles an engine is marked `slow` (each costs a
prefill+decode compile pair, ~15-25 s on the CI CPU). The paged-vs-slot
greedy equivalence on a shared trace lives with the other equivalence
pins in tests/test_serve_equivalence.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, PagedEngine
from ddp_practice_tpu.serve.kv_pages import GARBAGE_BLOCK, BlockAllocator
from ddp_practice_tpu.serve.scheduler import FakeClock, Request, Scheduler

VOCAB = 32


# ------------------------------------------------------------- host-only
def test_allocator_is_deterministic_and_reuses_freed_blocks():
    a = BlockAllocator(8)  # blocks 1..7 allocatable; 0 is the garbage block
    first = a.alloc(3)
    assert first == [1, 2, 3]
    second = a.alloc(2)
    assert second == [4, 5]
    a.free(first)
    # freed blocks go to the BACK: older free blocks hand out first,
    # then the released ones in release order
    assert a.alloc(4) == [6, 7, 1, 2]
    assert a.num_used == 6 and a.num_free == 1


def test_allocator_exhaustion_returns_none_without_side_effects():
    a = BlockAllocator(4)
    assert a.alloc(5) is None          # all-or-nothing: nothing consumed
    assert a.num_free == 3
    got = a.alloc(3)
    assert got == [1, 2, 3]
    assert a.alloc(1) is None
    a.free([2])
    assert a.alloc(1) == [2]


def test_allocator_rejects_bad_frees_and_sizes():
    a = BlockAllocator(4)
    with pytest.raises(ValueError):
        a.free([1])                    # never allocated
    with pytest.raises(ValueError):
        a.alloc(-1)
    with pytest.raises(ValueError):
        BlockAllocator(1)              # garbage block only — no pool
    assert a.alloc(0) == []


# ------------------------------------------------------- engine (compiles)
@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=32, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _paged(lm, **kw):
    model, params = lm
    kw.setdefault("max_slots", 2)
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("block_size", 8)
    kw.setdefault("max_blocks_per_slot", 4)
    return PagedEngine(model, params, EngineConfig(**kw))


@pytest.mark.slow
def test_freed_block_contents_never_visible_to_new_occupant(lm, devices):
    """A released request's K/V stays in its blocks; the next occupant of
    those blocks must decode exactly its solo tokens — masking to the
    slot's own written positions is what makes reuse safe."""
    # 2 real blocks total: B can only run inside A's released pages
    eng = _paged(lm, max_slots=1, max_blocks_per_slot=2, num_blocks=3)
    pa, pb = [3, 1, 4, 1, 5], [2, 7, 1]

    sa = eng.admit(pa, max_positions=8)
    for _ in range(8):
        eng.step()
    blocks_a = [int(b) for b in eng._pt[sa, : int(eng._nblk[sa])]]
    eng.release(sa)

    sb = eng.admit(pb, max_positions=8)
    got = [int(eng.step()[sb]) for _ in range(8)]
    blocks_b = [int(b) for b in eng._pt[sb, : int(eng._nblk[sb])]]

    solo = _paged(lm, max_slots=1, max_blocks_per_slot=2, num_blocks=3)
    ss = solo.admit(pb, max_positions=8)
    want = [int(solo.step()[ss]) for _ in range(8)]
    assert got == want
    # the reuse actually happened: B decoded inside A's old pages
    assert set(blocks_b) == set(blocks_a)


@pytest.mark.slow
def test_page_tables_grow_across_block_boundaries(lm, devices):
    """Decode crossing a block boundary draws blocks from the admit-time
    reservation; the page-table row and allocator agree at every step."""
    eng = _paged(lm, max_slots=2, block_size=8, max_blocks_per_slot=4)
    s = eng.admit([1, 2, 3], max_positions=16)   # bucket 8 -> 1 block now
    assert int(eng._nblk[s]) == 1
    assert int(eng._resv[s]) == 2                # ceil(24/8)=3 worst - 1
    for i in range(16):
        eng.step()
    # context 8+16=24 -> 3 blocks, reservation drained
    assert eng.context_len(s) == 24
    assert int(eng._nblk[s]) == 3 and int(eng._resv[s]) == 0
    rows = [int(b) for b in eng._pt[s, :3]]
    assert len(set(rows)) == 3 and GARBAGE_BLOCK not in rows
    # stepping past the admit-time reservation refuses loudly BEFORE
    # touching the allocator (no leaked blocks)
    free_before = eng.blocks.num_free
    with pytest.raises(RuntimeError, match="reservation"):
        eng.step()
    assert eng.blocks.num_free == free_before
    used_before = eng.blocks.num_used
    eng.release(s)
    assert eng.blocks.num_used == used_before - 3


@pytest.mark.slow
def test_block_exhaustion_queues_instead_of_crashing(lm, devices):
    """admit_gate answers "later" when blocks are reserved away; a direct
    over-admit raises; the scheduler turns "later" into queueing and the
    queued request runs after a release frees pages."""
    # pool of 6 real blocks; each request reserves 3 (bucket 8 + 16 new)
    eng = _paged(lm, max_slots=4, block_size=8, max_blocks_per_slot=3,
                 num_blocks=7)
    assert eng.admit_gate(3, 16) == "ok"
    s0 = eng.admit([1, 2, 3], max_positions=16)
    s1 = eng.admit([4, 5], max_positions=16)
    assert eng.admit_gate(3, 16) == "later"      # 0 unreserved blocks left
    assert eng.make_room() is False              # nothing to rewind
    with pytest.raises(RuntimeError):
        eng.admit([6], max_positions=16)
    # never: outgrows per-slot capacity / the whole pool
    assert eng.admit_gate(3, 100) == "never"

    from ddp_practice_tpu.serve.metrics import ServeMetrics

    metrics = ServeMetrics()
    sched = Scheduler(eng, clock=FakeClock(), metrics=metrics)
    for slot in (s0, s1):
        eng.release(slot)
    for rid in range(3):                          # only 2 fit at once
        assert sched.submit(Request(rid=rid, prompt=[1 + rid],
                                    max_new_tokens=16))
    done = sched.run_until_idle()
    assert [c.status for c in done] == ["length"] * 3
    assert eng.blocks.num_used == 0
    # the block gauges are RESERVATION-aware (what admission actually
    # gates on), and read all-free once the pool drains
    assert metrics.blocks_free.value == eng.blocks_available == 6
    assert metrics.block_occupancy.value == 0.0


@pytest.mark.slow
def test_long_context_outgrows_model_max_len(lm, devices, compile_guard):
    """The paged headline: a request keeps decoding past the model's
    max_len (slot-engine hard ceiling) as long as blocks exist — RoPE
    positions are unbounded and the span is the slot's own pages."""
    model, _ = lm
    eng = _paged(lm, block_size=8, max_blocks_per_slot=6)  # cap 48 > 32
    assert eng.max_context > model.max_len
    s = eng.admit([3, 1, 4, 1, 5])
    toks = [int(eng.step()[s]) for _ in range(4)]
    with compile_guard(eng):                      # growth never recompiles
        for _ in range(36):
            toks.append(int(eng.step()[s]))
    assert eng.context_len(s) == 48 > model.max_len
    assert all(0 <= t < VOCAB for t in toks)


@pytest.mark.slow
def test_churn_is_compile_free_after_warmup(lm, devices, compile_guard):
    """Two programs per bucket set, pinned via the conftest helper:
    arbitrary admit/step/release churn after warmup compiles nothing."""
    eng = _paged(lm)
    slot = eng.admit([1, 2, 3], max_positions=8)
    eng.step()
    eng.release(slot)
    assert eng.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
    }
    rng = np.random.default_rng(7)
    with compile_guard(eng):
        for _ in range(5):
            n = int(rng.integers(1, 9))
            s = eng.admit(rng.integers(0, VOCAB, n).tolist(),
                          max_positions=8)
            for _ in range(int(rng.integers(1, 8))):
                eng.step()
            eng.release(s)
