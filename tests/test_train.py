"""Training-step and end-to-end loop tests (single device).

The end-to-end contract is the reference's: accuracy climbs well above
chance within the epoch budget (origin_main.py reaches 91.55% on MNIST in
3 epochs; here on the synthetic stand-in dataset we require >90%)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step
from ddp_practice_tpu.train.loop import fit


def _tiny_setup():
    cfg = TrainConfig(optimizer="adam", learning_rate=1e-3)
    model = create_model("convnet")
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(0)
    state = create_state(
        model, tx, rng=rng, sample_input=jnp.zeros((1, 28, 28, 1))
    )
    return model, tx, state


def test_clip_norm_bounds_sgd_update():
    """With SGD lr and clip C, the param delta's global norm is exactly
    lr*min(C, ||g||): clipping rescales the whole gradient tree, applied
    BEFORE the optimizer."""
    import optax

    lr, clip = 0.5, 1e-3
    cfg = TrainConfig(optimizer="sgd", learning_rate=lr, clip_norm=clip)
    tx = make_optimizer(cfg)
    params = {"w": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([12.0])}
    grads = params  # global norm 13 >> clip
    opt_state = tx.init(params)
    updates, _ = tx.update(grads, opt_state, params)
    got = optax.global_norm(updates)
    np.testing.assert_allclose(float(got), lr * clip, rtol=1e-6)
    # below the threshold, clipping is a no-op
    small = jax.tree.map(lambda g: g * 1e-6, grads)
    updates, _ = tx.update(small, tx.init(params), params)
    np.testing.assert_allclose(
        float(optax.global_norm(updates)), lr * 13.0 * 1e-6, rtol=1e-5
    )


@pytest.mark.fast
def test_train_step_decreases_loss():
    model, tx, state = _tiny_setup()
    step = make_train_step(model, tx)
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.uniform(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 16), jnp.int32),
        "weight": jnp.ones((16,), jnp.float32),
    }
    losses = []
    for _ in range(20):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
    assert int(state.step) == 20


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_fit_reaches_reference_accuracy_contract():
    """The 91%-in-3-epochs contract (README.md:199) on the synthetic MNIST
    stand-in. Uses the parity budget: 3 epochs, batch 32."""
    cfg = TrainConfig(
        dataset="synthetic",
        epochs=3,
        batch_size=32,
        optimizer="adam",       # synthetic task; SGD 1e-4 parity run is the
        learning_rate=1e-3,     # full-MNIST config, too slow for CI
        log_every_steps=0,
        mesh=MeshConfig(data=1),
    )
    summary = fit(cfg)
    assert summary["accuracy"] > 0.90, summary
    assert summary["steps"] == 3 * (4096 // 32)


def test_grad_accumulation_matches_big_batch():
    """SGD with accum_steps=k over k micro-batches of size b == one step
    on the concatenated k*b batch (mean-of-means == mean of the whole for
    equal micro-batch sizes). A BN-free model (tiny ViT): BatchNorm's
    batch statistics legitimately differ between micro and full batches,
    so the equivalence claim is per-sample-normalized models only."""
    model = create_model(
        "vit_tiny", hidden_dim=32, depth=1, num_heads=2, mlp_dim=64,
        patch_size=7,
    )
    rng = np.random.default_rng(1)
    images = rng.uniform(size=(16, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)

    def run(cfg, batches):
        tx = make_optimizer(cfg)
        state = create_state(
            model, tx, rng=jax.random.PRNGKey(0),
            sample_input=jnp.zeros((2, 28, 28, 1)),
        )
        step = make_train_step(model, tx)
        for img, lbl in batches:
            batch = {
                "image": jnp.asarray(img), "label": jnp.asarray(lbl),
                "weight": jnp.ones((len(lbl),), jnp.float32),
            }
            state, _ = step(state, batch)
        return state.params

    micro = run(
        TrainConfig(optimizer="sgd", learning_rate=1e-2, accum_steps=4),
        [(images[i * 4:(i + 1) * 4], labels[i * 4:(i + 1) * 4])
         for i in range(4)],
    )
    big = run(
        TrainConfig(optimizer="sgd", learning_rate=1e-2),
        [(images, labels)],
    )
    for a, b in zip(jax.tree.leaves(micro), jax.tree.leaves(big)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-7, rtol=0
        )


def test_sgd_parity_hyperparams():
    """Optimizer defaults match the reference: SGD, lr 1e-4, unscaled
    (ddp_main.py:125; README.md:506)."""
    cfg = TrainConfig()
    assert cfg.learning_rate == 1e-4
    assert cfg.optimizer == "sgd"
    assert cfg.epochs == 3
    assert cfg.batch_size == 32
    assert cfg.seed == 3407
    assert not cfg.scale_lr_by_replicas
