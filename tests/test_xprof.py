"""XProf trace parser (utils/xprof.py).

Builds a minimal .xplane.pb BY HAND (raw protobuf wire format — the
schema field ids the parser documents) and checks the summary extracts
device time, categories, and bytes correctly. Runs protoc like the real
path does; no TPU or TensorBoard needed.
"""

import struct

import pytest

from ddp_practice_tpu.utils.xprof import op_summary


def _tag(field, wire):
    return bytes([(field << 3) | wire])


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vi(field, n) -> bytes:
    return _tag(field, 0) + _varint(n)


def _xplane() -> bytes:
    def stat_meta(mid, name):
        return _ld(5, _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, name.encode())))

    def event_meta(mid, name, cat_ref, nbytes):
        stats = _ld(5, _vi(1, 24) + _ld(5, cat_ref.encode()))
        stats += _ld(5, _vi(1, 31) + _vi(4, nbytes))
        return _ld(4, _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, name.encode()) + stats))

    def event(mid, dur_ps):
        st = _ld(4, _vi(1, 2) + _vi(3, dur_ps))
        return _ld(4, _vi(1, mid) + st)

    line = _ld(2, b"XLA Ops") + event(7, 1_000_000) + event(8, 3_000_000)
    plane = (
        _ld(2, b"/device:TPU:0 (fake)")
        + stat_meta(2, "device_duration_ps")
        + stat_meta(24, "hlo_category")
        + stat_meta(31, "bytes_accessed")
        + event_meta(7, "%fusion.1 = f32[8] fusion(...)", "loop fusion", 4096)
        + event_meta(8, "%conv.2 = f32[8] convolution(...)",
                     "convolution fusion", 65536)
        + _ld(3, line)
    )
    return _ld(1, plane)


def test_op_summary_roundtrip(tmp_path):
    p = tmp_path / "fake.xplane.pb"
    p.write_bytes(_xplane())
    try:
        s = op_summary(str(p))
    except FileNotFoundError as e:  # pragma: no cover — protoc missing
        pytest.skip(f"protoc unavailable: {e}")
    assert s["total_ps"] == 4_000_000
    cats = s["categories"]
    assert cats["loop fusion"]["ps"] == 1_000_000
    assert cats["loop fusion"]["bytes"] == 4096
    assert cats["convolution fusion"]["ps"] == 3_000_000
    assert cats["convolution fusion"]["count"] == 1
    assert s["ops"][("convolution fusion", "%conv.2")] == 3_000_000


@pytest.mark.fast
def test_directory_discovery_and_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        op_summary(str(tmp_path))
    sub = tmp_path / "plugins" / "profile" / "x"
    sub.mkdir(parents=True)
    (sub / "host.xplane.pb").write_bytes(_xplane())
    assert op_summary(str(tmp_path))["total_ps"] == 4_000_000
