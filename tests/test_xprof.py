"""XProf trace parser (utils/xprof.py).

Two coverage layers:

- a minimal .xplane.pb built BY HAND (raw protobuf wire format — the
  schema field ids the parser documents), run through protoc like the
  real path (skips where protoc is unavailable);
- a checked-in `protoc --decode_raw` TEXT fixture
  (tests/data/xplane_decode_raw.txt) pinned against `op_summary_text`
  directly — the field-id parser keeps tier-1 coverage even where the
  protoc round trip can't run.
"""

import os
import struct

import pytest

from ddp_practice_tpu.utils.xprof import op_summary, op_summary_text


def _tag(field, wire):
    return bytes([(field << 3) | wire])


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vi(field, n) -> bytes:
    return _tag(field, 0) + _varint(n)


def _xplane() -> bytes:
    def stat_meta(mid, name):
        return _ld(5, _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, name.encode())))

    def event_meta(mid, name, cat_ref, nbytes):
        stats = _ld(5, _vi(1, 24) + _ld(5, cat_ref.encode()))
        stats += _ld(5, _vi(1, 31) + _vi(4, nbytes))
        return _ld(4, _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, name.encode()) + stats))

    def event(mid, dur_ps):
        st = _ld(4, _vi(1, 2) + _vi(3, dur_ps))
        return _ld(4, _vi(1, mid) + st)

    line = _ld(2, b"XLA Ops") + event(7, 1_000_000) + event(8, 3_000_000)
    plane = (
        _ld(2, b"/device:TPU:0 (fake)")
        + stat_meta(2, "device_duration_ps")
        + stat_meta(24, "hlo_category")
        + stat_meta(31, "bytes_accessed")
        + event_meta(7, "%fusion.1 = f32[8] fusion(...)", "loop fusion", 4096)
        + event_meta(8, "%conv.2 = f32[8] convolution(...)",
                     "convolution fusion", 65536)
        + _ld(3, line)
    )
    return _ld(1, plane)


def test_op_summary_roundtrip(tmp_path):
    p = tmp_path / "fake.xplane.pb"
    p.write_bytes(_xplane())
    try:
        s = op_summary(str(p))
    except FileNotFoundError as e:  # pragma: no cover — protoc missing
        pytest.skip(f"protoc unavailable: {e}")
    assert s["total_ps"] == 4_000_000
    cats = s["categories"]
    assert cats["loop fusion"]["ps"] == 1_000_000
    assert cats["loop fusion"]["bytes"] == 4096
    assert cats["convolution fusion"]["ps"] == 3_000_000
    assert cats["convolution fusion"]["count"] == 1
    assert s["ops"][("convolution fusion", "%conv.2")] == 3_000_000


_FIXTURE = os.path.join(
    os.path.dirname(__file__), "data", "xplane_decode_raw.txt"
)


@pytest.mark.fast
def test_decode_raw_fixture_pins_field_id_parser():
    """The checked-in decode_raw text drives the parser with NO protoc:
    per-category aggregation, repeated events under one metadata id,
    %while container skip, bytes per execution, non-matching line and
    non-device plane both ignored."""
    with open(_FIXTURE) as f:
        s = op_summary_text(f.read())
    assert s["planes"] == 1                 # host plane filtered out
    assert s["total_ps"] == 8_000_000       # %while's 700000 excluded
    cats = s["categories"]
    assert cats["loop fusion"] == {
        "ps": 2_000_000, "count": 1, "bytes": 131072,
    }
    # two executions of the same op: ps summed, bytes charged per run
    assert cats["convolution"] == {
        "ps": 6_000_000, "count": 2, "bytes": 131072,
    }
    assert "control flow" not in cats       # only the skipped %while
    assert s["ops"][("loop fusion", "%fusion.3")] == 2_000_000
    assert s["ops"][("convolution", "%convolution.7")] == 6_000_000


@pytest.mark.fast
def test_decode_raw_fixture_unmatched_filters_raise():
    with open(_FIXTURE) as f:
        text = f.read()
    with pytest.raises(ValueError, match="no plane matching"):
        op_summary_text(text, device_substr="GPU")
    with pytest.raises(ValueError, match="no plane matching"):
        op_summary_text(text, line_substr="No Such Line")


@pytest.mark.fast
def test_directory_discovery_and_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        op_summary(str(tmp_path))
    sub = tmp_path / "plugins" / "profile" / "x"
    sub.mkdir(parents=True)
    (sub / "host.xplane.pb").write_bytes(_xplane())
    try:
        total = op_summary(str(tmp_path))["total_ps"]
    except FileNotFoundError as e:
        if "protoc" in str(e):  # discovery worked; decoding needs protoc
            pytest.skip(f"protoc unavailable: {e}")
        raise
    assert total == 4_000_000
