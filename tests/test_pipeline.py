"""Pipeline-parallelism tests: GPipe schedule over the 'pipe' mesh axis.

Contract: the pipelined forward equals the depth-sequential application of
the SAME stacked block parameters (GPipe reorders compute, not math), its
gradients match, and a full sharded train step runs with stage-sharded
parameters composed with data parallelism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step


MODEL_KW = dict(depth=4, hidden_dim=32, num_heads=4, mlp_dim=64, patch_size=4)


@pytest.fixture()
def pipe_mesh(devices):
    mesh = build_mesh(MeshConfig(data=2, pipe=4))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


def _images(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(size=(n, 16, 16, 3)), jnp.float32)


def _models():
    piped = create_model(
        "vit_tiny_pipe", num_stages=4, num_microbatches=2, **MODEL_KW
    )
    seq = create_model("vit_tiny_pipe", num_stages=1, **MODEL_KW)
    return piped, seq


def _partial_manual(fn, *args, **kwargs):
    """Run a PARTIAL-manual shard_map composition (the pipeline island
    manual over 'pipe'/'data' while GSPMD partitions the stage body over
    the remaining axes). This image's old XLA cannot compile that —
    "PartitionId instruction is not supported for SPMD partitioning"
    (ROADMAP standing debt) — which is an environment limit, not a code
    bug: skip on exactly that error, fail on anything else."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:
        if "PartitionId" in str(e):
            pytest.skip("old XLA: PartitionId unsupported under "
                        "partial-manual SPMD partitioning")
        raise


@pytest.mark.fast
def test_pipeline_forward_matches_sequential(pipe_mesh):
    piped, seq = _models()
    x = _images()
    variables = seq.init(jax.random.PRNGKey(0), x)
    want = seq.apply(variables, x)
    got = piped.apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential(pipe_mesh):
    piped, seq = _models()
    x = _images(seed=1)
    variables = seq.init(jax.random.PRNGKey(1), x)

    def loss(model, params):
        return jnp.sum(model.apply({"params": params}, x) ** 2)

    g_seq = jax.grad(lambda p: loss(seq, p))(variables["params"])
    g_pipe = jax.grad(lambda p: loss(piped, p))(variables["params"])
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)


def test_pipeline_sharded_train_step(pipe_mesh):
    """Stage-sharded params + data-sharded batch through make_train_step."""
    model = create_model(
        "vit_tiny_pipe", num_stages=4, num_microbatches=2, **MODEL_KW
    )
    cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
    tx = make_optimizer(cfg)
    sample = jnp.zeros((8, 16, 16, 3))

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    rules = param_sharding_rules("vit_tiny_pipe")
    shardings = shard_state(abstract, pipe_mesh, rules)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))

    # block params are really split across the pipe axis
    qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.addressable_shards[0].data.shape[0] == qkv.shape[0] // 4

    bsh = batch_sharding(pipe_mesh)
    step = make_train_step(
        model, tx, mesh=pipe_mesh, state_shardings=shardings, batch_shardings=bsh
    )
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.uniform(size=(8, 16, 16, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 8), jnp.int32),
        "weight": jnp.ones((8,), jnp.float32),
    }
    before = np.asarray(jax.tree.leaves(state.params)[0])
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    after = np.asarray(jax.tree.leaves(state.params)[0])
    assert not np.allclose(before, after)  # params actually updated


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_pipeline_composes_sequence_parallelism(devices, sp_impl):
    """SP x PP: ring/Ulysses attention opens a nested shard_map island
    over the still-automatic 'seq' axis inside each pipeline stage; the
    sharded pipelined forward must match the sequential dense apply."""
    mesh = build_mesh(MeshConfig(data=2, seq=2, pipe=2))
    set_current_mesh(mesh)
    try:
        piped = create_model(
            "vit_tiny_pipe", num_stages=2, num_microbatches=2,
            seq_axis=MeshConfig.AXIS_SEQ, sp_impl=sp_impl, **MODEL_KW
        )
        seq = create_model("vit_tiny_pipe", num_stages=1, **MODEL_KW)
        x = _images()
        variables = seq.init(jax.random.PRNGKey(0), x)
        want = seq.apply(variables, x)
        got = _partial_manual(piped.apply, variables, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )
    finally:
        set_current_mesh(None)


@pytest.fixture()
def tp_pipe_mesh(devices):
    mesh = build_mesh(MeshConfig(data=2, pipe=2, tensor=2))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


def test_pipeline_composes_tensor_parallelism_forward(tp_pipe_mesh):
    """TP x PP: the pipelined forward on params sharded over BOTH 'pipe'
    (stage dim) and 'tensor' (Megatron inner dims) matches the sequential
    unsharded apply — the pipeline shard_map is manual over 'pipe'/'data'
    only, so GSPMD partitions the stage body over 'tensor'."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.tree_util import tree_map_with_path

    piped = create_model(
        "vit_tiny_pipe", num_stages=2, num_microbatches=2, **MODEL_KW
    )
    seq = create_model("vit_tiny_pipe", num_stages=1, **MODEL_KW)
    x = _images()
    variables = seq.init(jax.random.PRNGKey(0), x)
    want = seq.apply(variables, x)

    rules = param_sharding_rules("vit_tiny_pipe")
    sharded_params = tree_map_with_path(
        lambda p, leaf: jax.device_put(
            leaf, NamedSharding(tp_pipe_mesh, rules(p, leaf) or P())
        ),
        variables["params"],
    )
    # the TP spec really splits the stacked qkv kernel over 'tensor' too
    qkv = sharded_params["blocks"]["attn"]["qkv"]["kernel"]
    shard_shape = qkv.addressable_shards[0].data.shape
    assert shard_shape[0] == qkv.shape[0] // 2  # pipe (stage dim)
    assert shard_shape[3] == qkv.shape[3] // 2  # tensor (heads dim)

    got = _partial_manual(piped.apply, {"params": sharded_params}, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_pipeline_tensor_parallel_train_step(tp_pipe_mesh):
    """A full dp x pp x tp train step: state sharded by the composed rules,
    loss finite, params update."""
    model = create_model(
        "vit_tiny_pipe", num_stages=2, num_microbatches=2, **MODEL_KW
    )
    cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
    tx = make_optimizer(cfg)
    sample = jnp.zeros((8, 16, 16, 3))

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    rules = param_sharding_rules("vit_tiny_pipe")
    shardings = shard_state(abstract, tp_pipe_mesh, rules)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
    shard_shape = qkv.addressable_shards[0].data.shape
    assert shard_shape[0] == qkv.shape[0] // 2
    assert shard_shape[3] == qkv.shape[3] // 2

    bsh = batch_sharding(tp_pipe_mesh)
    step = make_train_step(
        model, tx, mesh=tp_pipe_mesh, state_shardings=shardings,
        batch_shardings=bsh,
    )
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.uniform(size=(8, 16, 16, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 8), jnp.int32),
        "weight": jnp.ones((8,), jnp.float32),
    }
    before = np.asarray(jax.tree.leaves(state.params)[0])
    state, metrics = _partial_manual(step, state, batch)
    assert np.isfinite(float(metrics["loss"]))
    after = np.asarray(jax.tree.leaves(state.params)[0])
    assert not np.allclose(before, after)
