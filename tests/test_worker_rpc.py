"""serve/rpc.py transport seam — host-pure (no jax, no subprocesses).

The framing and failure semantics the cross-process fleet stands on:
length-prefixed JSON round trips, per-call timeouts raise RpcTimeout
instead of hanging (the SIGSTOP containment primitive), transport
retries reconnect with the shared backoff schedule, and a handler
error answers on the wire instead of killing the connection.
"""

import json
import socket
import threading
import time

import pytest

from ddp_practice_tpu.serve.rpc import (
    MAX_FRAME_BYTES,
    RpcClient,
    RpcError,
    RpcRemoteError,
    RpcServer,
    RpcTimeout,
    recv_frame,
    send_frame,
)


# ----------------------------------------------------------------- framing
def test_frame_roundtrip_including_unicode_and_nesting():
    a, b = socket.socketpair()
    try:
        msg = {"op": "x", "tokens": list(range(500)),
               "s": "naïve — ünïcödé", "nested": {"a": [1, {"b": None}]}}
        send_frame(a, msg)
        assert recv_frame(b) == msg
        # frames alternate cleanly: a second message on the same pipe
        send_frame(b, {"ok": True})
        assert recv_frame(a) == {"ok": True}
    finally:
        a.close()
        b.close()


def test_frame_rejects_oversize_and_truncation():
    a, b = socket.socketpair()
    try:
        with pytest.raises(RpcError):
            send_frame(a, {"big": "x" * (MAX_FRAME_BYTES + 1)})
        # a corrupt length prefix refuses before allocating
        a.sendall(b"\xff\xff\xff\xff")
        with pytest.raises(RpcError):
            recv_frame(b)
    finally:
        a.close()
        b.close()
    # peer closing mid-frame is an RpcError, not a hang
    a, b = socket.socketpair()
    a.sendall(b"\x00\x00\x00\x10half")
    a.close()
    try:
        with pytest.raises(RpcError):
            recv_frame(b)
    finally:
        b.close()


# ------------------------------------------------------- client <-> server
def test_server_dispatch_and_remote_error():
    calls = []

    def echo(req):
        calls.append(req)
        return {"echo": req.get("payload")}

    def boom(req):
        raise ValueError("handler exploded")

    with RpcServer({"echo": echo, "boom": boom}) as srv:
        with RpcClient("127.0.0.1", srv.port, timeout_s=5.0) as c:
            r = c.call("echo", payload=[1, 2, 3])
            assert r["ok"] and r["echo"] == [1, 2, 3]
            # handler exception -> error reply -> RpcRemoteError, and
            # the CONNECTION survives for the next call
            with pytest.raises(RpcRemoteError, match="handler exploded"):
                c.call("boom")
            with pytest.raises(RpcRemoteError, match="unknown op"):
                c.call("nope")
            assert c.call("echo", payload="still alive")["echo"] \
                == "still alive"
    assert len(calls) == 2


def test_call_times_out_on_stalled_handler():
    """A handler that never answers (the SIGSTOP stand-in) must raise
    RpcTimeout within the per-call budget, not hang the caller."""
    release = threading.Event()

    def stall(req):
        release.wait(10.0)
        return {}

    with RpcServer({"stall": stall}) as srv:
        c = RpcClient("127.0.0.1", srv.port, timeout_s=0.2, retries=0)
        t0 = time.monotonic()
        with pytest.raises(RpcTimeout):
            c.call("stall")
        assert time.monotonic() - t0 < 5.0
        c.close()
        release.set()


def test_transport_retry_reconnects_with_backoff():
    """Kill the first server; the client's retry budget reconnects to a
    replacement on the same port and the call SUCCEEDS — the sleep hook
    records the deterministic backoff schedule."""
    srv = RpcServer({"ping": lambda req: {"pong": 1}})
    port = srv.port
    slept = []
    c = RpcClient("127.0.0.1", port, timeout_s=2.0, retries=3,
                  retry_base_s=0.01, sleep=slept.append)
    assert c.call("ping")["pong"] == 1
    srv.close()
    # connection now points at a dead listener; next call must retry.
    # A replacement comes up on the same port mid-retry:
    replacement = {}

    def bring_back():
        time.sleep(0.05)
        replacement["srv"] = RpcServer(
            {"ping": lambda req: {"pong": 2}}, port=port
        )

    t = threading.Thread(target=bring_back)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while True:
            try:
                r = c.call("ping")
                break
            except RpcError:
                assert time.monotonic() < deadline
        assert r["pong"] == 2
        assert slept, "no backoff sleeps recorded on the retry path"
    finally:
        t.join()
        c.close()
        replacement["srv"].close()


# ------------------------------------------------------------- federation
def test_scrape_federator_relabels_and_judges_live_servers():
    """Two real (in-process) TelemetryServers federated: /metrics lines
    gain worker="N" labels plus the fleet_* series, /healthz rolls the
    per-worker verdicts up — and a worker going away flips the verdict
    without crashing the scrape."""
    from ddp_practice_tpu.utils.metrics import MetricsRegistry
    from ddp_practice_tpu.utils.telemetry import (
        ScrapeFederator,
        TelemetryServer,
        _relabel_metric_line,
    )

    # the relabel helper alone, incl. labelled and unlabelled lines
    assert _relabel_metric_line('x_total 3', 'worker="1"') \
        == 'x_total{worker="1"} 3'
    assert _relabel_metric_line('x{a="b c"} 3.5', 'worker="0"') \
        == 'x{worker="0",a="b c"} 3.5'
    assert _relabel_metric_line("# HELP x y", 'worker="0"') \
        == "# HELP x y"

    regs = [MetricsRegistry(), MetricsRegistry()]
    regs[0].counter("serve_tokens_total").inc(7)
    regs[1].gauge("serve_queue_depth").set(2)
    servers = [
        TelemetryServer(registry=regs[i],
                        health_fn=lambda i=i: {i: "healthy"}, port=0)
        for i in range(2)
    ]
    state = {
        i: {"host": "127.0.0.1", "port": servers[i].port, "pid": 100 + i,
            "up": True, "state": "running", "restarts": 0,
            "heartbeat_age_s": 0.1}
        for i in range(2)
    }
    fed = ScrapeFederator(lambda: state, stale_after_s=5.0)
    text = fed.render_text()
    assert 'serve_tokens_total{worker="0"} 7' in text
    assert 'serve_queue_depth{worker="1"} 2' in text
    assert 'fleet_worker_up{worker="0"} 1' in text
    body = fed.healthz()
    assert body["status"] == "HEALTHY"
    assert body["workers"]["0"]["status"] == "healthy"
    # stale heartbeat degrades even while the scrape answers
    state[1]["heartbeat_age_s"] = 60.0
    body = fed.healthz()
    assert body["workers"]["1"]["status"] == "stale"
    assert body["status"] == "DEGRADED"
    # a dead worker (server gone, target down) is a verdict, not a crash
    servers[0].close()
    state[0]["up"] = False
    state[0]["port"] = None
    body = fed.healthz()
    assert body["workers"]["0"]["status"] == "dead"
    text = fed.render_text()
    assert 'fleet_worker_up{worker="0"} 0' in text
    state[1]["heartbeat_age_s"] = 0.1
    # all dead -> DEAD (the federated server would then serve 503)
    servers[1].close()
    state[1]["up"] = False
    assert fed.healthz()["status"] == "DEAD"


def test_federated_healthz_fn_serves_503_on_dead():
    """TelemetryServer's healthz_fn hook: the federated body rides
    /healthz verbatim and the 503-on-DEAD orchestrator contract keys
    off its status field."""
    import http.client

    from ddp_practice_tpu.utils.telemetry import TelemetryServer

    verdict = {"status": "HEALTHY", "fleet": True, "workers": {}}
    srv = TelemetryServer(healthz_fn=lambda: verdict, port=0)
    try:
        def get():
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=5)
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            body = json.loads(r.read())
            conn.close()
            return r.status, body

        status, body = get()
        assert status == 200 and body["fleet"] is True
        verdict["status"] = "DEAD"
        status, body = get()
        assert status == 503 and body["status"] == "DEAD"
    finally:
        srv.close()


def test_connect_refused_raises_after_retries():
    slept = []
    # a port nothing listens on: bind-then-close to find a free one
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    c = RpcClient("127.0.0.1", port, timeout_s=0.5, retries=2,
                  retry_base_s=0.001, sleep=slept.append)
    with pytest.raises(RpcError):
        c.call("ping")
    assert len(slept) == 2  # one backoff per extra attempt
