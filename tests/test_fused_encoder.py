"""Fused Pallas encoder layer (ops/fused_encoder.py).

Contract: the one-kernel layer computes the SAME function as the unfused
flax EncoderBlock — forward outputs match, and the hand-derived backward
kernel's gradients (params AND input) match autodiff of the unfused
block. Runs in interpret mode on the CPU backend, compiled on TPU
(BENCHMARKS.md records the hardware numbers under both of its
measurement conventions: 44% vs 18.7% MFU per-layer forward, 30.5% vs
17.0% train in the bench suite's convention, at d=192).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.models.vit import EncoderBlock

HEADS, MLP, D, S = 3, 768, 192, 64


def _block(**kw):
    return EncoderBlock(HEADS, MLP, **kw)


def _x(b=4, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal((b, S, D)), jnp.float32
    )


@pytest.fixture(scope="module")
def variables():
    return _block().init(jax.random.PRNGKey(0), _x(1))


@pytest.mark.fast
def test_forward_matches_unfused(devices, variables):
    x = _x(b=6, seed=1)  # 6 also exercises _fit_tile on a non-pow2 batch
    want = _block().apply(variables, x)
    got = _block(fused=True).apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("bwd_impl", ["kernel", "reference"])
def test_grads_match_unfused(devices, variables, bwd_impl):
    """Param AND input grads from the fused layer equal unfused autodiff
    — for the hand-derived Pallas backward and the recompute fallback."""
    from ddp_practice_tpu.ops.fused_encoder import fused_encoder_layer

    x = _x(b=4, seed=2)
    p = variables["params"]
    block = _block()

    def fused_loss(p, x):
        y = fused_encoder_layer(
            x, p, num_heads=HEADS, compute_dtype=jnp.float32,
            reference_apply=lambda pp, xx: block.apply({"params": pp}, xx),
            bwd_impl=bwd_impl,
        )
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def unfused_loss(p, x):
        return jnp.sum(block.apply({"params": p}, x).astype(jnp.float32) ** 2)

    gp_w, gx_w = jax.grad(unfused_loss, argnums=(0, 1))(p, x)
    gp_f, gx_f = jax.grad(fused_loss, argnums=(0, 1))(p, x)
    flat_w = jax.tree_util.tree_leaves_with_path(gp_w)
    flat_f = jax.tree.leaves(gp_f)
    for (path, w), f in zip(flat_w, flat_f):
        np.testing.assert_allclose(
            np.asarray(f), np.asarray(w), rtol=2e-4, atol=2e-4,
            err_msg=jax.tree_util.keystr(path),
        )
    np.testing.assert_allclose(
        np.asarray(gx_f), np.asarray(gx_w), rtol=2e-4, atol=2e-4
    )


def test_vit_model_fused_matches_unfused(devices):
    """Model-level: vit_tiny(fused=True) logits == the per-op model."""
    kw = dict(depth=2, hidden_dim=D, num_heads=HEADS, mlp_dim=MLP)
    dense = create_model("vit_tiny", **kw)
    fused = create_model("vit_tiny", fused=True, **kw)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    v = dense.init(jax.random.PRNGKey(0), x, train=False)
    np.testing.assert_allclose(
        np.asarray(fused.apply(v, x)), np.asarray(dense.apply(v, x)),
        rtol=2e-5, atol=2e-5,
    )


def test_fused_train_step_moves_params(devices):
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import make_train_step

    model = create_model(
        "vit_tiny", fused=True, depth=2, hidden_dim=D, num_heads=HEADS,
        mlp_dim=MLP,
    )
    tx = make_optimizer(TrainConfig(optimizer="adamw", learning_rate=1e-3))
    state = create_state(
        model, tx, rng=jax.random.PRNGKey(0),
        sample_input=jnp.zeros((1, 32, 32, 3)),
    )
    rng = np.random.default_rng(4)
    batch = {
        "image": jnp.asarray(rng.uniform(size=(8, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 8), jnp.int32),
    }
    before = np.asarray(jax.tree.leaves(state.params)[0])
    state, metrics = make_train_step(model, tx)(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert not np.allclose(before, np.asarray(jax.tree.leaves(state.params)[0]))


def test_fused_gates_unsupported_configs(devices, variables):
    x = _x(b=2)
    # causal is SUPPORTED since round 4 (test_causal_fused_matches_unfused);
    # rope and dropout still keep the per-op path
    with pytest.raises(ValueError, match="fused"):
        EncoderBlock(HEADS, MLP, fused=True, rope=True).apply(variables, x)
    with pytest.raises(ValueError, match="fused"):
        EncoderBlock(HEADS, MLP, fused=True, dropout_rate=0.1).apply(
            variables, x, False, True
        )


def test_causal_fused_matches_unfused(devices):
    """Round 4: the fused kernel's causal path (decoder-LM blocks) —
    forward AND both grads against the unfused causal block."""
    from ddp_practice_tpu.ops.fused_encoder import fused_encoder_layer

    block = _block(causal=True)
    variables = block.init(jax.random.PRNGKey(3), _x(1))
    x = _x(b=4, seed=4)
    p = variables["params"]

    want = block.apply(variables, x)
    got = _block(causal=True, fused=True).apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )

    def fused_loss(p, x):
        y = fused_encoder_layer(
            x, p, num_heads=HEADS, compute_dtype=jnp.float32, causal=True,
        )
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def unfused_loss(p, x):
        return jnp.sum(block.apply({"params": p}, x).astype(jnp.float32) ** 2)

    gp_w, gx_w = jax.grad(unfused_loss, argnums=(0, 1))(p, x)
    gp_f, gx_f = jax.grad(fused_loss, argnums=(0, 1))(p, x)
    flat_w = jax.tree_util.tree_leaves_with_path(gp_w)
    flat_f = jax.tree.leaves(gp_f)
    for (path, w), f in zip(flat_w, flat_f):
        np.testing.assert_allclose(
            np.asarray(f), np.asarray(w), rtol=2e-4, atol=2e-4,
            err_msg=jax.tree_util.keystr(path),
        )
    np.testing.assert_allclose(
        np.asarray(gx_f), np.asarray(gx_w), rtol=2e-4, atol=2e-4
    )


def test_causality_of_fused_kernel(devices):
    """Perturbing a late token must not change earlier outputs."""
    block = _block(causal=True, fused=True)
    variables = block.init(jax.random.PRNGKey(5), _x(1))
    x = _x(b=2, seed=6)
    y1 = block.apply(variables, x)
    x2 = x.at[:, -1].add(3.0)
    y2 = block.apply(variables, x2)
    np.testing.assert_allclose(
        np.asarray(y1[:, :-1]), np.asarray(y2[:, :-1]), rtol=1e-5, atol=1e-5
    )
    assert float(jnp.max(jnp.abs(y1[:, -1] - y2[:, -1]))) > 1e-3


def test_fused_lm_matches_unfused(devices):
    """TransformerLM(fused=True): same logits and grads as the unfused
    model (params are identical — fused is an execution strategy)."""
    # depth 2 keeps the layer-chaining pin (residual handoff between
    # fused layers); mlp 128 halves the interpret-mode cost that made
    # this the suite's slowest test (18s at mlp 256)
    kw = dict(vocab_size=64, max_len=32, hidden_dim=128, depth=2,
              num_heads=2, mlp_dim=128)
    lm = create_model("lm_tiny", policy=None, **kw)
    lm_f = create_model("lm_tiny", policy=None, fused=True, **kw)
    toks = jnp.asarray(
        np.random.default_rng(7).integers(0, 64, (2, 32)), jnp.int32
    )
    variables = lm.init(jax.random.PRNGKey(8), toks)
    want = lm.apply(variables, toks)
    got = lm_f.apply(variables, toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )

    def loss(p, model):
        lg = model.apply({"params": p}, toks).astype(jnp.float32)
        return jnp.sum(lg ** 2) / lg.size

    gw = jax.grad(lambda p: loss(p, lm))(variables["params"])
    gf = jax.grad(lambda p: loss(p, lm_f))(variables["params"])
    for (path, w), f in zip(
        jax.tree_util.tree_leaves_with_path(gw), jax.tree.leaves(gf)
    ):
        np.testing.assert_allclose(
            np.asarray(f), np.asarray(w), rtol=3e-4, atol=3e-4,
            err_msg=jax.tree_util.keystr(path),
        )
