"""Chaos tests: the fault-tolerant router under a deterministic FaultPlan.

The acceptance scenario (ISSUE 2): a seeded Poisson trace on fake-clock
replicas, a replica killed mid-decode, and three invariants that make
failover trustworthy rather than hopeful:

1. NONE LOST — every submitted request ends in a defined terminal
   status (ok / shed / timeout / rejected / error), crash or not;
2. TOKEN IDENTITY — a migrated request's greedy tokens equal a
   fault-free single-replica run's (failover re-admits prompt +
   tokens-so-far; greedy decoding is a pure function of the prefix);
3. NO NEW COMPILES — failover re-prefills land in already-warmed
   buckets on survivors (jit cache sizes pinned before/after).

Everything replays bit-for-bit: FakeClock time, seeded trace, seeded
fault plan, deterministic backoff jitter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import (
    EngineConfig,
    FakeClock,
    FaultPlan,
    FaultSpec,
    Request,
    RouterConfig,
    Scheduler,
    SlotEngine,
    make_router,
)
from ddp_practice_tpu.serve.bench import build_trace

VOCAB = 32

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _trace(n, rate_hz=50.0, seed=11, max_new=(3, 7), plen=(2, 6)):
    return build_trace(
        n_requests=n, rate_hz=rate_hz, vocab=VOCAB,
        prompt_len_range=plen, max_new_range=max_new, seed=seed,
    )


def _reference_tokens(lm, trace, engine_cfg):
    """Fault-free single-replica run (the PR-1 path) of the same trace."""
    model, params = lm
    engine = SlotEngine(model, params, engine_cfg)
    sched = Scheduler(engine, clock=FakeClock(step_s=0.01),
                      max_queue=len(trace))
    for t in trace:
        sched.submit(Request(
            rid=t["rid"], prompt=t["prompt"],
            max_new_tokens=t["max_new_tokens"],
        ))
    sched.run_until_idle()
    return {c.rid: c.tokens for c in sched.completions}


def _drive(router, trace):
    """Replay arrivals on the router's fake clock until the fleet drains."""
    i = 0
    while not (i >= len(trace) and router.idle):
        while i < len(trace) \
                and trace[i]["arrival"] <= router.clock.now():
            t = trace[i]
            router.submit(Request(
                rid=t["rid"], prompt=t["prompt"],
                max_new_tokens=t["max_new_tokens"],
                arrival=t["arrival"],
            ))
            i += 1
        router.step()
    return router.completions


ENGINE_CFG = EngineConfig(
    max_slots=2, max_len=96, prompt_buckets=(32,), temperature=0.0,
)


@pytest.mark.slow  # ~25 s: three engines (reference + 2-replica fleet)
def test_failover_token_identity_none_lost(devices, lm):
    """Kill replica 0 mid-decode: migrated requests finish with tokens
    identical to the fault-free run, nothing is lost, survivors compile
    nothing new."""
    model, params = lm
    trace = _trace(12)
    want = _reference_tokens(lm, trace, ENGINE_CFG)

    plan = FaultPlan([FaultSpec(kind="crash", tick=6, replica=0)])
    clock = FakeClock(step_s=0.01)
    router = make_router(
        model, params, 2, ENGINE_CFG, clock=clock, max_queue=64,
        config=RouterConfig(max_retries=2, retry_jitter=0.0),
        fault_plan=plan,
    )
    router.warmup()
    warm = router.compile_stats()
    assert warm[1] == {"prefill_compiles": 1, "decode_compiles": 1}

    comps = _drive(router, trace)

    # none lost: every request has exactly one terminal completion
    by_rid = {c.rid: c for c in comps}
    assert sorted(by_rid) == [t["rid"] for t in trace]
    assert all(
        c.status in ("eos", "length", "shed", "timeout", "rejected",
                     "error")
        for c in comps
    )
    # the crash actually hit in-flight work and failover fired
    assert router.metrics.failovers.value >= 1
    assert router.states()[0] == "dead" and router.states()[1] == "healthy"
    # token identity: every served request — including the migrated ones,
    # whose continuation ran as prompt+prefix on the survivor — matches
    # the fault-free single-replica run bit-for-bit (greedy)
    served = [c for c in comps if c.status in ("eos", "length")]
    assert served, "no request completed"
    for c in served:
        assert c.tokens == want[c.rid], f"rid {c.rid} diverged"
    # under this plan nothing needed shedding: the survivor absorbed all
    assert all(c.status == "length" for c in comps)
    # failover re-prefills landed in the warmed bucket: zero new compiles
    assert router.compile_stats()[1] == warm[1]

    # the same plan replays bit-identically (chaos must be reproducible)
    router2 = make_router(
        model, params, 2, ENGINE_CFG, clock=FakeClock(step_s=0.01),
        max_queue=64, config=RouterConfig(max_retries=2, retry_jitter=0.0),
        fault_plan=FaultPlan.from_json(plan.to_json()),
    )
    router2.warmup()
    comps2 = {c.rid: c for c in _drive(router2, trace)}
    for rid, c in by_rid.items():
        assert comps2[rid].tokens == c.tokens
        assert comps2[rid].status == c.status
        assert comps2[rid].finish == c.finish


@pytest.mark.slow  # ~15 s: two engines (reference + single-replica fleet)
def test_nan_and_admit_faults_are_retried_to_identical_tokens(devices, lm):
    """A NaN in one slot's logits and an injected admission failure each
    poison ONE request, which the router retries to a completion that is
    token-identical to the fault-free run — the batch never notices."""
    model, params = lm
    cfg = EngineConfig(max_slots=2, max_len=96, prompt_buckets=(16,),
                       temperature=0.0)
    trace = _trace(4, rate_hz=1000.0, seed=3)  # all arrive ~immediately
    want = _reference_tokens(lm, trace, cfg)

    plan = FaultPlan([
        FaultSpec(kind="admit_fail", tick=1, replica=0),
        FaultSpec(kind="nan_logits", tick=4, replica=0, slot=0),
    ])
    router = make_router(
        model, params, 1, cfg, clock=FakeClock(step_s=0.01), max_queue=64,
        config=RouterConfig(max_retries=3, retry_base_s=0.01,
                            retry_jitter=0.0, trip_after=10),
        fault_plan=plan,
    )
    router.warmup()
    comps = _drive(router, trace)

    by_rid = {c.rid: c for c in comps}
    assert sorted(by_rid) == [0, 1, 2, 3]
    # both faults consumed a retry; the breaker never tripped
    assert router.metrics.retries.value >= 2
    assert router.metrics.breaker_trips.value == 0
    assert router.states()[0] == "healthy"
    # every request ends ok with the fault-free tokens — the NaN cost a
    # retry, not an answer, and not anyone else's answer
    for c in comps:
        assert c.status == "length"
        assert c.tokens == want[c.rid], f"rid {c.rid} diverged"


def test_brownout_sheds_low_priority_and_caps_budget(devices, lm):
    """Overload flips brown-out on: queued low-priority work is shed
    with reason=brownout, new low-priority arrivals shed at the door,
    new high-priority arrivals get a capped token budget, and the mode
    clears when pressure drains."""
    model, params = lm
    cfg = EngineConfig(max_slots=1, max_len=96, prompt_buckets=(8,),
                       temperature=0.0)
    router = make_router(
        model, params, 1, cfg, clock=FakeClock(step_s=0.01), max_queue=64,
        config=RouterConfig(brownout_on=2.0, brownout_off=0.5,
                            brownout_max_new=2, shed_priority=1,
                            retry_jitter=0.0),
    )
    router.warmup()
    pri = [0, 0, 0, 1, 1, 0]
    for rid, p in enumerate(pri):
        assert router.submit(Request(
            rid=rid, prompt=[1 + rid, 2], max_new_tokens=6, priority=p,
        ))
    router.step()  # pressure (5 queued + 1 active) / 1 slot >> 2.0
    assert router.brownout
    assert router.metrics.brownout_active.value == 1
    snap = router.metrics.registry.snapshot()
    assert snap["serve_sheds_total{reason=brownout}"] == 2  # rids 3, 4
    # door behavior while browned out
    assert not router.submit(Request(rid=6, prompt=[7, 2],
                                     max_new_tokens=6, priority=1))
    assert router.submit(Request(rid=7, prompt=[8, 2],
                                 max_new_tokens=6, priority=0))
    router.run_until_idle()
    by_rid = {c.rid: c for c in router.completions}
    assert {r: by_rid[r].status for r in (3, 4, 6)} == {
        3: "shed", 4: "shed", 6: "shed",
    }
    # pre-brown-out admissions keep their full budget; the brown-out-era
    # admission is capped at brownout_max_new
    for rid in (0, 1, 2, 5):
        assert by_rid[rid].status == "length"
        assert len(by_rid[rid].tokens) == 6
    assert by_rid[7].status == "length" and len(by_rid[7].tokens) == 2
    # drained: pressure back under the floor, mode cleared
    assert not router.brownout
    assert router.metrics.brownout_active.value == 0
    snap = router.metrics.registry.snapshot()
    assert snap["serve_sheds_total{reason=brownout}"] == 3


def test_slo_burn_trips_brownout_below_pressure_threshold(devices, lm):
    """THE SLO-brownout pin (ISSUE 5): the router browns out from SLO
    burn with fleet pressure far below `brownout_on`, behaves exactly
    like a pressure brown-out while engaged (door sheds, budget caps),
    and disengages with hysteresis only after the slow window clears —
    all under FakeClock."""
    from ddp_practice_tpu.serve.slo import SLOConfig, SLOWatchdog
    from ddp_practice_tpu.utils.trace import TraceRecorder

    model, params = lm
    cfg = EngineConfig(max_slots=4, max_len=96, prompt_buckets=(8,),
                       temperature=0.0)
    clock = FakeClock(step_s=0.01)
    tracer = TraceRecorder(clock=clock)
    watchdog = SLOWatchdog(
        SLOConfig(availability=0.9, fast_window_s=0.5, slow_window_s=2.0,
                  trip_burn=2.0, resolve_burn=1.0, min_events=3),
        clock=clock, tracer=tracer,
    )
    router = make_router(
        model, params, 1, cfg, clock=clock, max_queue=64,
        # brownout_on is unreachable: ONLY the SLO can trip the mode
        config=RouterConfig(brownout_on=50.0, brownout_off=0.4,
                            brownout_max_new=2, shed_priority=1,
                            retry_jitter=0.0),
        tracer=tracer, slo=watchdog,
    )
    router.warmup()
    tracer.clear()
    # five already-expired deadlines -> five "timeout" completions in
    # one tick: availability burn trips while the fleet sits idle
    for rid in range(5):
        router.submit(Request(rid=rid, prompt=[1 + rid, 2],
                              max_new_tokens=4, deadline=-1.0))
    router.step()
    assert watchdog.active
    assert router.brownout
    assert router.metrics.brownout_active.value == 1
    # the point: pressure is nowhere near the pressure trigger
    assert router.metrics.fleet_pressure.value < 50.0
    # engaged brown-out behaves identically to the pressure one
    assert not router.submit(Request(rid=10, prompt=[3, 2],
                                     max_new_tokens=6, priority=1))
    assert router.submit(Request(rid=11, prompt=[4, 2],
                                 max_new_tokens=6, priority=0))
    router.run_until_idle()
    by_rid = {c.rid: c for c in router.completions}
    assert by_rid[10].status == "shed"
    assert by_rid[11].status == "length" and len(by_rid[11].tokens) == 2
    # anti-windup: rid 10's shed was the BROWN-OUT's own doing — it
    # must not count as an availability failure, or the alert would
    # feed itself and the mode could never disengage under sustained
    # low-priority traffic. Bad events seen = the 5 original timeouts.
    assert sum(
        flags.get("availability", False)
        for _, flags in watchdog._events
    ) == 5
    # pressure is BELOW brownout_off already; the mode must still hold
    # until the SLO resolves (disengage needs both)
    assert router.metrics.fleet_pressure.value <= 0.4
    assert router.brownout
    # tick past the slow window: watchdog resolves, brown-out clears
    for _ in range(400):
        router.step()
        if not router.brownout:
            break
    assert not watchdog.active
    assert not router.brownout
    assert [e for _, e, _ in watchdog.alert_log] == ["trip", "resolve"]
    # the trace records the whole story: slo alert edges + a brownout_on
    # instant attributed to the SLO trigger, and it validates clean
    from tools.check_traces import validate

    trace = tracer.to_chrome_trace()
    assert validate(trace) == []
    by_name = {}
    for ev in trace["traceEvents"]:
        by_name.setdefault(ev["name"], []).append(ev)
    assert "slo_alert" in by_name and "slo_resolve" in by_name
    assert by_name["brownout_on"][0]["args"]["trigger"] == "slo"
    assert "brownout_off" in by_name


def test_permanently_dead_fleet_sheds_not_hangs(devices, lm):
    """The none-lost invariant with NOWHERE to fail over: a 1-replica
    fleet whose only replica dies for good must give every in-flight and
    queued request a terminal shed — not cycle the retry heap forever
    (run_until_idle would never drain and the bench loop would spin)."""
    model, params = lm
    cfg = EngineConfig(max_slots=2, max_len=96, prompt_buckets=(8,),
                       temperature=0.0)
    plan = FaultPlan([FaultSpec(kind="crash", tick=3, replica=0)])
    router = make_router(
        model, params, 1, cfg, clock=FakeClock(step_s=0.01), max_queue=64,
        config=RouterConfig(retry_jitter=0.0), fault_plan=plan,
    )
    router.warmup()
    for rid in range(4):
        router.submit(Request(rid=rid, prompt=[1 + rid, 2],
                              max_new_tokens=8))
    router.run_until_idle(max_ticks=500)  # must DRAIN, not raise
    assert router.idle
    by_rid = {c.rid: c for c in router.completions}
    assert sorted(by_rid) == [0, 1, 2, 3]
    assert all(c.status in ("length", "shed") for c in router.completions)
    assert any(c.status == "shed" for c in router.completions)
    snap = router.metrics.registry.snapshot()
    assert snap["serve_sheds_total{reason=no_replica}"] >= 1
    # and the front door gives the same fast no
    assert not router.submit(Request(rid=9, prompt=[3], max_new_tokens=2))
    assert router.completions[-1].status == "shed"


def test_replica_recovery_after_down_window(devices, lm):
    """A crash with down_s > 0: the breaker's half-open probe finds the
    replica alive after the window and it serves again (state returns
    to healthy, later requests complete on a 2-replica fleet)."""
    model, params = lm
    cfg = EngineConfig(max_slots=2, max_len=96, prompt_buckets=(8,),
                       temperature=0.0)
    plan = FaultPlan([
        FaultSpec(kind="crash", tick=2, replica=0, down_s=0.2),
    ])
    router = make_router(
        model, params, 2, cfg, clock=FakeClock(step_s=0.01), max_queue=64,
        config=RouterConfig(probe_base_s=0.05, probe_jitter=0.0,
                            retry_jitter=0.0),
        fault_plan=plan,
    )
    router.warmup()
    for rid in range(4):
        router.submit(Request(rid=rid, prompt=[1 + rid, 2, 3],
                              max_new_tokens=4))
    router.run_until_idle()
    assert all(c.status == "length" for c in router.completions)
    assert router.metrics.breaker_trips.value == 1
    # keep ticking past the down window: a probe revives replica 0
    for _ in range(60):
        if router.states()[0] == "healthy":
            break
        router.step()
    assert router.states()[0] == "healthy"
    # and it actually serves again
    router.submit(Request(rid=99, prompt=[5, 6], max_new_tokens=3))
    router.run_until_idle()
    assert {c.rid: c.status for c in router.completions}[99] == "length"


# --------------------------------------------- one-way submit cast (PR 15)
def _cast_handle(drop_first_cast: bool):
    """Host-pure RemoteReplicaHandle over a scripted one-way wire: the
    stub client records every submit cast, optionally drops the first
    frame on the floor, and answers the reconcile poll's `confirm` ask
    from a worker-side dedup map keyed by rid — the exact seam the
    fire-and-forget path trusts."""
    from ddp_practice_tpu.serve.supervisor import (
        RemoteReplicaHandle,
        Supervisor,
        SupervisorConfig,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    wire = {"casts": [], "delivered": [], "drop": drop_first_cast,
            "seen": {}}

    class Client:
        def cast(self, op, **fields):
            assert op == "submit"
            req = fields["request"]
            wire["casts"].append(req["rid"])
            if wire["drop"]:
                wire["drop"] = False
                return                      # the frame never arrives
            # worker-side dedup by rid: a replayed cast is absorbed,
            # never double-admitted
            if req["rid"] not in wire["seen"]:
                wire["seen"][req["rid"]] = True
                wire["delivered"].append(req)

        def call(self, op, **fields):
            if op == "poll":
                reply = {
                    "completions": [], "watermark": 0, "inflight": [],
                    "stats": {"queue": 0,
                              "active": len(wire["delivered"]),
                              "max_slots": 2},
                    "version": 1,
                }
                if fields.get("confirm"):
                    # absent = never saw the rid (the lost-frame answer)
                    reply["confirmed"] = {
                        str(r): True for r in fields["confirm"]
                        if r in wire["seen"]
                    }
                return reply
            return {"ok": True}

        def close(self):
            pass

    class Worker:
        def __init__(self, spec):
            self.pid = 4242
            self.spec = spec
            self.client = Client()
            self.telemetry_port = 0

        def poll(self):
            return None

        def kill_signal(self, sig):
            pass

        def reap(self, timeout_s=5.0):
            pass

    spec = WorkerSpec(engine={"max_slots": 2, "prompt_buckets": [8]},
                      max_queue=4)
    clock = FakeClock(step_s=0.01)
    sup = Supervisor([spec], SupervisorConfig(), spawn_fn=Worker,
                     spawn_in_thread=False, clock=clock)
    sup.start()
    return RemoteReplicaHandle(0, sup, spec, clock=clock), clock, wire


def test_dropped_submit_cast_redispatches_exactly_once():
    """The PR-15 fire-and-forget seam: a submit cast lost on the wire
    is re-dispatched by confirm-on-poll reconciliation EXACTLY once —
    same rid (idempotent at the worker's dedup map), no further casts
    once the worker confirms, and the request never leaves
    `outstanding` (the salvage point failover needs)."""
    h, clock, wire = _cast_handle(drop_first_cast=True)
    h.submit(Request(rid=9, prompt=[1, 2], max_new_tokens=4,
                     arrival=0.0))
    assert wire["casts"] == [9] and wire["delivered"] == []
    assert 9 in h.outstanding

    clock.advance(10.0)            # past the poll throttle
    h.step()                       # confirm ask -> "never saw rid 9"
    assert wire["casts"] == [9, 9]             # re-cast, once
    assert [r["rid"] for r in wire["delivered"]] == [9]

    for _ in range(3):             # confirmed: reconciliation goes quiet
        clock.advance(10.0)
        h.step()
    assert wire["casts"] == [9, 9]             # no third dispatch
    assert [r["rid"] for r in wire["delivered"]] == [9]
    assert 9 in h.outstanding      # still inflight, awaiting completion


def test_duplicate_cast_is_absorbed_by_rid_dedup():
    """The other half of at-least-once delivery: when the first frame
    DID land but its confirmation hadn't yet, a conservative re-cast
    reaches the worker as a duplicate rid and must admit nothing new."""
    h, clock, wire = _cast_handle(drop_first_cast=False)
    h.submit(Request(rid=3, prompt=[1, 2, 3], max_new_tokens=4,
                     arrival=0.0))
    assert [r["rid"] for r in wire["delivered"]] == [3]
    # replay the same frame (the reconcile path's worst case)
    h._client().cast("submit", request=h._request_dict(
        h.outstanding[3]["req"]))
    assert wire["casts"] == [3, 3]
    assert [r["rid"] for r in wire["delivered"]] == [3]   # dedup held
    clock.advance(10.0)
    h.step()                       # poll confirms; unconfirmed clears
    assert wire["casts"] == [3, 3]
