"""FSDP/ZeRO-3 tests: data-axis parameter sharding.

The reference fully replicates params + optimizer state per process
(ddp_main.py:117-125; SURVEY §2.3 "FSDP/ZeRO — No"). Here ZeRO-3 is a
PartitionSpec choice; these tests assert (a) leaves really are sharded
over 'data' (and optimizer mirrors with them), (b) training numerics are
identical to replicated DP, and (c) FSDP composes with tensor parallelism.
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.fsdp import fsdp_rules
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step


def _batch(n, seed=0, hw=28, ch=1):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.uniform(size=(n, hw, hw, ch)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, n), jnp.int32),
        "weight": jnp.ones((n,), jnp.float32),
    }


def _make(mesh_cfg, *, model_name="convnet", rules=None, model_kwargs=None,
          sample_shape=(1, 28, 28, 1)):
    cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
    mesh = build_mesh(mesh_cfg)
    model = create_model(model_name, **(model_kwargs or {}))
    tx = make_optimizer(cfg)

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=jnp.zeros(sample_shape))

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, rules)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    bsh = batch_sharding(mesh)
    step = make_train_step(
        model, tx, mesh=mesh, state_shardings=shardings, batch_shardings=bsh
    )
    return mesh, state, step, bsh


@pytest.mark.fast
def test_fsdp_leaves_sharded_over_data(devices):
    rules = fsdp_rules(8, None, min_leaf_size=128)
    mesh, state, _, _ = _make(MeshConfig(data=8), rules=rules)
    # dense kernel (1568, 10): dim 0 divisible by 8 -> sharded over 'data'
    k = state.params["Dense_0"]["kernel"]
    assert "data" in str(k.sharding.spec), k.sharding.spec
    assert k.addressable_shards[0].data.shape[0] == k.shape[0] // 8
    # optimizer state mirrors the same layout (ZeRO partitioning): total
    # addressable bytes for that leaf are 1/8 of the logical array
    assert k.addressable_shards[0].data.size * 8 == k.size


def test_fsdp_small_leaves_stay_replicated(devices):
    rules = fsdp_rules(8, None, min_leaf_size=1024)
    mesh, state, _, _ = _make(MeshConfig(data=8), rules=rules)
    b = state.params["Conv_0"]["bias"]  # (16,) — tiny, stays replicated
    assert b.sharding.spec == jax.sharding.PartitionSpec() or all(
        s is None for s in b.sharding.spec
    )


def test_fsdp_matches_replicated_dp(devices):
    batches = [_batch(8, seed=s) for s in range(3)]
    _, s_rep, step_rep, _ = _make(MeshConfig(data=8))
    _, s_fsdp, step_fsdp, _ = _make(
        MeshConfig(data=8), rules=fsdp_rules(8, None, min_leaf_size=128)
    )
    for b in batches:
        s_rep, m_rep = step_rep(s_rep, b)
        s_fsdp, m_fsdp = step_fsdp(s_fsdp, b)
    np.testing.assert_allclose(
        float(m_rep["loss"]), float(m_fsdp["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(s_rep.params), jax.tree.leaves(s_fsdp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fsdp_composes_with_tp(devices):
    """TP rules claim 'tensor' dims; FSDP shards a free dim over 'data'."""
    tp = param_sharding_rules("vit_tiny")
    rules = fsdp_rules(2, tp, min_leaf_size=128)
    mesh, state, step, _ = _make(
        MeshConfig(data=2, tensor=4),
        model_name="vit_tiny",
        rules=rules,
        model_kwargs=dict(depth=2, hidden_dim=32, num_heads=4, mlp_dim=64),
        sample_shape=(1, 16, 16, 3),
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    spec = str(qkv.sharding.spec)
    assert "tensor" in spec and "data" in spec, spec
    state, metrics = step(state, _batch(8, seed=1, hw=16, ch=3))
    assert np.isfinite(float(metrics["loss"]))
