"""MoE / expert-parallelism tests.

Contracts: the dense one-hot gating respects capacity and produces
normalized combine weights; a single-expert MoE reduces exactly to a dense
MLP; and ViT-MoE trains under an 'expert'-sharded mesh with the
load-balance aux loss flowing into the total loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.ops.moe import MoEMlp, top_k_gating
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step


@pytest.mark.fast
def test_gating_capacity_and_normalization():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    dispatch, combine, aux, _ = top_k_gating(logits, k=2, capacity=3)
    d = np.asarray(dispatch)
    # every (expert, slot) receives at most one token per group
    assert d.sum(axis=1).max() <= 1.0 + 1e-6
    # capacity respected: at most C tokens per expert
    assert d.sum(axis=(1, 3)).max() <= 3 + 1e-6
    # each token dispatched at most k times
    assert d.sum(axis=(2, 3)).max() <= 2 + 1e-6
    # kept tokens have combine weights summing to 1
    c = np.asarray(combine).sum(axis=(2, 3))
    kept = d.sum(axis=(2, 3)) > 0
    np.testing.assert_allclose(c[kept], 1.0, rtol=1e-5)
    assert np.isfinite(float(aux))


def test_single_expert_equals_dense_mlp():
    """E=1, k=1, capacity >= T routes every token through the one expert:
    output must equal that expert's MLP applied densely."""
    layer = MoEMlp(num_experts=1, top_k=1, capacity_factor=1.0, mlp_dim=32)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 16)), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(variables, x)
    p = variables["params"]
    w1, b1 = p["expert_w_in"][0], p["expert_b_in"][0]
    w2, b2 = p["expert_w_out"][0], p["expert_b_out"][0]
    import flax.linen as nn

    want = nn.gelu(x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_all_tokens_kept_with_ample_capacity():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    dispatch, _, _, _ = top_k_gating(logits, k=1, capacity=16)
    assert np.asarray(dispatch).sum() == 2 * 16  # every token kept once


@pytest.fixture()
def expert_mesh(devices):
    mesh = build_mesh(MeshConfig(data=2, expert=4))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


def test_vit_moe_sharded_train_step(expert_mesh):
    model = create_model(
        "vit_tiny_moe",
        depth=2,
        hidden_dim=32,
        num_heads=4,
        mlp_dim=64,
        num_experts=4,
        top_k=2,
        moe_every=2,
    )
    cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
    tx = make_optimizer(cfg)
    sample = jnp.zeros((8, 16, 16, 3))

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    rules = param_sharding_rules("vit_tiny_moe")
    shardings = shard_state(abstract, expert_mesh, rules)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))

    w = state.params["block1"]["moe"]["expert_w_in"]
    assert w.addressable_shards[0].data.shape[0] == w.shape[0] // 4  # E-sharded

    bsh = batch_sharding(expert_mesh)
    step = make_train_step(
        model, tx, mesh=expert_mesh, state_shardings=shardings, batch_shardings=bsh
    )
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.uniform(size=(8, 16, 16, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 8), jnp.int32),
        "weight": jnp.ones((8,), jnp.float32),
    }
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_lm_moe_every_zero_is_dense_lm(devices):
    """lm_moe with moe_every=0 IS the dense decoder: identical param tree
    and bit-identical logits to lm_tiny — the MoE composition is additive,
    not a fork of the family."""
    kw = dict(vocab_size=32, max_len=32, hidden_dim=32, depth=2,
              num_heads=4, mlp_dim=64)
    moe0 = create_model("lm_moe", moe_every=0, **kw)
    dense = create_model("lm_tiny", **kw)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 32, (2, 16)), jnp.int32
    )
    v = dense.init(jax.random.PRNGKey(0), tokens)
    assert (
        jax.tree.structure(moe0.init(jax.random.PRNGKey(0), tokens))
        == jax.tree.structure(v)
    )
    np.testing.assert_array_equal(
        np.asarray(moe0.apply(v, tokens)), np.asarray(dense.apply(v, tokens))
    )


def test_lm_moe_sharded_train_step_with_router_metrics(expert_mesh):
    """dp x ep MoE LM: expert-sharded params train; the step surfaces
    router health (load fractions bounded, drop rate in [0,1])."""
    from ddp_practice_tpu.train.steps import make_lm_train_step

    model = create_model(
        "lm_moe", vocab_size=32, max_len=32, hidden_dim=32, depth=2,
        num_heads=4, mlp_dim=64, num_experts=4, moe_every=2,
    )
    cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
    tx = make_optimizer(cfg)
    sample = jnp.zeros((8, 16), jnp.int32)

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(
        abstract, expert_mesh, param_sharding_rules("lm_moe")
    )
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    w = state.params["block1"]["moe"]["expert_w_in"]
    assert w.addressable_shards[0].data.shape[0] == w.shape[0] // 4

    step = make_lm_train_step(
        model, tx, mesh=expert_mesh, state_shardings=shardings,
        batch_shardings=batch_sharding(expert_mesh),
    )
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 32, (8, 17)), jnp.int32
    )
    state, metrics = step(state, {"tokens": tokens})
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["moe_drop_rate"]) <= 1.0
    assert 0.0 <= float(metrics["moe_load_min"]) <= float(
        metrics["moe_load_max"]
    ) <= 1.0


def test_aux_loss_increases_total_loss(expert_mesh):
    """The sown aux loss reaches the optimized objective: total loss with
    aux weight > 0 differs from the pure CE value."""
    from ddp_practice_tpu.ops.losses import cross_entropy

    model = create_model(
        "vit_tiny_moe", depth=2, hidden_dim=32, num_heads=4, mlp_dim=64,
        num_experts=4, top_k=1, moe_every=2,
    )
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.uniform(size=(8, 16, 16, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits, updated = model.apply(
        variables, x, train=True, mutable=["intermediates"]
    )
    aux = sum(
        float(jnp.sum(leaf))
        for leaf in jax.tree.leaves(updated["intermediates"])
    )
    assert aux > 0.0  # switch loss is >= 1 at uniform routing, scaled by 0.01
    ce = float(cross_entropy(logits, labels))
    assert np.isfinite(ce)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_router_balances_over_training(devices):
    """VERDICT round-3 item 3: the balancing machinery (fixed Switch aux
    + aux-free selection bias) must actually BALANCE load over training,
    not just add a loss term. Trains a small lm_moe on the synthetic
    Markov corpus and asserts the router health trajectory: drop rate
    falls well below its early value, and no expert is dead at the end.
    """
    from ddp_practice_tpu.data.lm_corpus import synthetic_token_corpus
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _lm_train_step_fn

    seq, bsz = 128, 8
    corpus = synthetic_token_corpus(n_tokens=1 << 16, seed=11)
    windows = jnp.asarray(corpus.windows(seq))
    n_win = windows.shape[0]
    model = create_model(
        "lm_moe",
        policy=None,
        vocab_size=corpus.vocab_size,
        max_len=seq,
        hidden_dim=128,
        depth=2,
        num_heads=4,
        mlp_dim=256,
        moe_every=1,
        num_experts=8,
        # zero-headroom capacity so the INITIAL router skew produces real
        # drops for the balancers to fix (the default cf=2.0 gives this
        # small config so much slack that drops are 0 from step one and
        # the trajectory would assert nothing); the absolute <5% warm
        # claim is recorded by the cf=2.0 bench entry (BENCHMARKS.json
        # lm_moe: drop 0.0087 after 40 warm steps on this corpus)
        capacity_factor=1.0,
    )
    tx = make_optimizer(
        TrainConfig(model="lm_moe", optimizer="adamw", learning_rate=1e-3)
    )
    sample = jnp.zeros((bsz, seq), jnp.int32)
    state = create_state(
        model, tx, rng=jax.random.PRNGKey(0), sample_input=sample
    )
    assert state.batch_stats is not None  # the router bias lives here
    step = jax.jit(_lm_train_step_fn(model, tx))

    key = jax.random.PRNGKey(1)
    drops, load_mins = [], []
    for i in range(30):
        key, sub = jax.random.split(key)
        idx = jax.random.randint(sub, (bsz,), 0, n_win, jnp.int32)
        state, metrics = step(state, {"tokens": windows[idx]})
        drops.append(float(metrics["moe_drop_rate"]))
        load_mins.append(float(metrics["moe_load_min"]))

    early = float(np.mean(drops[:3]))
    late = float(np.mean(drops[-5:]))
    # the aux loss + selection bias must bite: late drops well under the
    # early rate (at capacity_factor 1.0 a per-group stochastic floor of
    # ~0.12 remains — headroom, not balancing, removes that part)
    assert late < early * 0.6, (early, late)
    assert late < 0.2, drops
    # no dead expert once warm
    assert float(np.mean(load_mins[-5:])) > 0.05, load_mins
    # the selection bias actually moved (the balancer ran)
    bias_leaves = jax.tree.leaves(state.batch_stats)
    assert any(float(jnp.max(jnp.abs(b))) > 0.0 for b in bias_leaves)


def test_group_size_permutation_exact():
    """group_size routing (round 4) must be a pure regrouping: with one
    expert and ample capacity nothing can drop, gates are 1, and the
    expert MLP is row-wise — so grouped (strided AND contiguous) outputs
    must match the ungrouped module EXACTLY. This pins the interleave
    permutation and its inverse."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_practice_tpu.ops.moe import MoEMlp

    x = jnp.asarray(
        np.random.default_rng(11).standard_normal((2, 64, 16)), jnp.float32
    )
    outs = {}
    for name, kw in [
        ("ungrouped", {}),
        ("strided", {"group_size": 16, "group_stride": True}),
        ("contig", {"group_size": 16, "group_stride": False}),
    ]:
        m = MoEMlp(num_experts=1, top_k=1, capacity_factor=4.0,
                   mlp_dim=32, expert_axis=None, **kw)
        params = m.init(jax.random.PRNGKey(0), x)
        outs[name] = m.apply(params, x)
    np.testing.assert_array_equal(
        np.asarray(outs["ungrouped"]), np.asarray(outs["strided"])
    )
    np.testing.assert_array_equal(
        np.asarray(outs["ungrouped"]), np.asarray(outs["contig"])
    )


def test_group_size_must_divide_seq():
    import jax
    import jax.numpy as jnp
    import pytest

    from ddp_practice_tpu.ops.moe import MoEMlp

    m = MoEMlp(num_experts=2, top_k=1, mlp_dim=32, group_size=48,
               expert_axis=None)
    x = jnp.zeros((1, 64, 16))
    with pytest.raises(ValueError, match="must divide"):
        m.init(jax.random.PRNGKey(0), x)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_sorted_impl_matches_dropless_einsum():
    """The sorted (counting-sort + grouped-matmul) expert path computes
    the SAME function as the einsum path when the latter has enough
    capacity to drop nothing — forward, parameter grads, and input
    grads (ops/moe.py MoEMlp impl)."""
    G, T, D, E, F, K = 2, 64, 32, 4, 64, 2
    x = jax.random.normal(jax.random.PRNGKey(1), (G, T, D), jnp.float32)
    kw = dict(num_experts=E, top_k=K, mlp_dim=F, bias_update_rate=0.0,
              expert_axis=None)
    # capacity_factor E/K makes capacity == T: dropless by construction
    m_e = MoEMlp(impl="einsum", capacity_factor=float(E) / K, **kw)
    m_s = MoEMlp(impl="sorted", **kw)
    v = m_e.init(jax.random.PRNGKey(0), x)

    def loss(params, mod, xx):
        y, _ = mod.apply(
            {"params": params, "batch_stats": v["batch_stats"]}, xx,
            mutable=["intermediates", "batch_stats"],
        )
        return jnp.sum(y * y)

    ye, _ = m_e.apply(v, x, mutable=["intermediates", "batch_stats"])
    ys, _ = m_s.apply(v, x, mutable=["intermediates", "batch_stats"])
    np.testing.assert_allclose(np.asarray(ye), np.asarray(ys),
                               rtol=2e-5, atol=2e-5)
    ge, gxe = jax.grad(loss, argnums=(0, 2))(v["params"], m_e, x)
    gs, gxs = jax.grad(loss, argnums=(0, 2))(v["params"], m_s, x)
    np.testing.assert_allclose(np.asarray(gxe), np.asarray(gxs),
                               rtol=5e-4, atol=5e-4)
    import jax.tree_util as jtu

    for (pe, le), (_, ls) in zip(
        jtu.tree_leaves_with_path(ge), jtu.tree_leaves_with_path(gs)
    ):
        np.testing.assert_allclose(
            np.asarray(le), np.asarray(ls), rtol=5e-4, atol=5e-4,
            err_msg=jtu.keystr(pe),
        )


def test_sorted_impl_router_metrics_and_bias_update():
    """Sorted path keeps the router-health contract: drop rate exactly 0,
    load fractions sum to 1, and the aux-free bias moves against
    measured overload just like the einsum path."""
    G, T, D, E, F, K = 2, 32, 16, 4, 32, 2
    x = jax.random.normal(jax.random.PRNGKey(2), (G, T, D), jnp.float32)
    m = MoEMlp(impl="sorted", num_experts=E, top_k=K, mlp_dim=F,
               bias_update_rate=0.05, expert_axis=None)
    v = m.init(jax.random.PRNGKey(0), x)
    _, mut = m.apply(v, x, mutable=["intermediates", "batch_stats"])
    inter = mut["intermediates"]
    drop = float(inter["moe_drop_rate"][0])
    load = np.asarray(inter["moe_load_frac"][0])
    assert drop == 0.0
    np.testing.assert_allclose(load.sum(), 1.0, rtol=1e-5)
    bias = np.asarray(mut["batch_stats"]["router_bias"])
    assert np.any(bias != 0.0)  # the online balancer moved


def test_assignment_permutation_is_counting_sort():
    """dest/inv from _assignment_permutation are mutually inverse and
    order assignments by (expert, arrival)."""
    from ddp_practice_tpu.ops.moe import _assignment_permutation

    rng = np.random.RandomState(0)
    cf = jnp.asarray(rng.randint(0, 5, size=64), jnp.int32)
    counts, dest, inv = _assignment_permutation(cf, 5)
    dest_np, inv_np = np.asarray(dest), np.asarray(inv)
    assert sorted(dest_np.tolist()) == list(range(64))
    np.testing.assert_array_equal(dest_np[inv_np], np.arange(64))
    sorted_experts = np.asarray(cf)[inv_np]
    assert (np.diff(sorted_experts) >= 0).all()
    np.testing.assert_array_equal(
        np.asarray(counts), np.bincount(np.asarray(cf), minlength=5)
    )


@pytest.mark.parametrize("cf,group_kw", [
    (1.0, {}),
    (1.25, {"group_size": 16}),
    (2.0, {"group_size": 16, "group_stride": False}),
])
def test_gather_impl_matches_einsum(cf, group_kw):
    """The gather path (per-slot lookup tables + custom gather-only
    VJPs, ops/moe.py _gather) computes the SAME function as the einsum
    path — same drops, same combine weights, same bias updates, same
    grads — across capacity regimes and routing groups. (The measured
    shootout left einsum the auto default — BENCHMARKS.md round-5 MoE
    section — so gather is opt-in; this equality keeps it honest.)"""
    import jax.tree_util as jtu

    G, T, D, E, F, K = 2, 64, 32, 4, 64, 2
    x = jax.random.normal(jax.random.PRNGKey(1), (G, T, D), jnp.float32)
    kw = dict(num_experts=E, top_k=K, mlp_dim=F, bias_update_rate=0.05,
              expert_axis=None, capacity_factor=cf, **group_kw)
    m_e = MoEMlp(impl="einsum", **kw)
    m_g = MoEMlp(impl="gather", **kw)
    v = m_e.init(jax.random.PRNGKey(0), x)

    ye, me = m_e.apply(v, x, mutable=["intermediates", "batch_stats"])
    yg, mg = m_g.apply(v, x, mutable=["intermediates", "batch_stats"])
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yg),
                               rtol=2e-5, atol=2e-5)
    assert (
        float(me["intermediates"]["moe_drop_rate"][0])
        == float(mg["intermediates"]["moe_drop_rate"][0])
    )
    np.testing.assert_allclose(
        np.asarray(me["batch_stats"]["router_bias"]),
        np.asarray(mg["batch_stats"]["router_bias"]),
    )

    def loss(params, mod, xx):
        y, _ = mod.apply(
            {"params": params, "batch_stats": v["batch_stats"]}, xx,
            mutable=["intermediates", "batch_stats"],
        )
        return jnp.sum(y * y)

    ge, gxe = jax.grad(loss, argnums=(0, 2))(v["params"], m_e, x)
    gg, gxg = jax.grad(loss, argnums=(0, 2))(v["params"], m_g, x)
    np.testing.assert_allclose(np.asarray(gxe), np.asarray(gxg),
                               rtol=5e-4, atol=5e-4)
    for (pe, le), (_, lg) in zip(
        jtu.tree_leaves_with_path(ge), jtu.tree_leaves_with_path(gg)
    ):
        np.testing.assert_allclose(
            np.asarray(le), np.asarray(lg), rtol=5e-4, atol=5e-4,
            err_msg=f"cf={cf} {jtu.keystr(pe)}",
        )


def test_expert_choice_single_expert_is_dense_mlp():
    """router='expert_choice' with one expert at capacity T picks every
    token once with gate 1.0 — exactly the dense expert MLP."""
    G, T, D, F = 2, 32, 16, 32
    x = jax.random.normal(jax.random.PRNGKey(1), (G, T, D), jnp.float32)
    m = MoEMlp(router="expert_choice", num_experts=1, top_k=1,
               capacity_factor=1.0, mlp_dim=F, expert_axis=None)
    v = m.init(jax.random.PRNGKey(0), x)
    y, mut = m.apply(v, x, mutable=["intermediates"])
    p = v["params"]
    h = jax.nn.gelu(x @ p["expert_w_in"][0] + p["expert_b_in"][0])
    ref = h @ p["expert_w_out"][0] + p["expert_b_out"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert float(mut["intermediates"]["moe_drop_rate"][0]) == 0.0


def test_expert_choice_perfect_balance_no_state():
    """Expert choice fills every buffer slot (load exactly 1/E), needs
    no batch_stats balancing state, and the router still receives
    gradients through the combine weights."""
    G, T, D, F, E, K = 2, 32, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(1), (G, T, D), jnp.float32)
    m = MoEMlp(router="expert_choice", num_experts=E, top_k=K,
               capacity_factor=1.0, mlp_dim=F, expert_axis=None)
    v = m.init(jax.random.PRNGKey(0), x)
    assert "batch_stats" not in v
    y, mut = m.apply(v, x, mutable=["intermediates"])
    np.testing.assert_allclose(
        np.asarray(mut["intermediates"]["moe_load_frac"][0]),
        np.full(E, 1.0 / E), rtol=1e-6,
    )
    g = jax.grad(lambda pp: jnp.sum(m.apply(
        {"params": pp}, x, mutable=["intermediates"])[0] ** 2))(v["params"])
    assert float(jnp.linalg.norm(g["router"]["kernel"])) > 0


@pytest.mark.fast
def test_expert_choice_gating_slots_full():
    """Every (expert, slot) pair selects exactly one token — zero
    padding by construction (ops/moe.py expert_choice_gating)."""
    from ddp_practice_tpu.ops.moe import expert_choice_gating

    logits = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 4))
    dispatch, combine, uncovered = expert_choice_gating(logits, capacity=4)
    np.testing.assert_allclose(np.asarray(jnp.sum(dispatch, axis=1)), 1.0)
    assert 0.0 <= float(uncovered) <= 1.0
    # combine weights are the router gates at the picked pairs
    gates = jax.nn.softmax(logits, axis=-1)
    w = np.asarray(jnp.sum(combine, axis=-1))  # (G, T, E), nonzero where picked
    picked = np.asarray(jnp.sum(dispatch, axis=-1)) > 0
    np.testing.assert_allclose(w[picked], np.asarray(gates)[picked], rtol=1e-6)


def test_expert_choice_lm_trains():
    """lm_moe with moe_router='expert_choice' trains end-to-end (loss
    decreases) through the standard step machinery."""
    model = create_model(
        "lm_moe", policy=None, vocab_size=64, max_len=32,
        hidden_dim=32, depth=2, num_heads=4, mlp_dim=64,
        num_experts=4, moe_router="expert_choice", capacity_factor=1.0,
    )
    import optax

    from ddp_practice_tpu.train.state import create_state
    from ddp_practice_tpu.train.steps import make_lm_train_step

    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (4, 33), 0, 64, dtype=jnp.int32
    )
    state = create_state(model, optax.adam(1e-2), rng=jax.random.PRNGKey(1),
                         sample_input=tokens[:, :-1])
    step = make_lm_train_step(model, optax.adam(1e-2))
    first = None
    for i in range(8):
        state, metrics = step(state, {"tokens": tokens})
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_expert_choice_lm_generates():
    """An expert-choice lm_moe checkpoint generates through the KV-cache
    decode path: EC has no serving story at T=1 (every expert would pick
    the lone token), so decode falls back to per-token top-k over the
    gates — the standard EC serving approximation (ops/moe.py)."""
    from ddp_practice_tpu.inference import make_generate_fn

    model = create_model(
        "lm_moe", policy=None, vocab_size=32, max_len=64,
        hidden_dim=32, depth=2, num_heads=4, mlp_dim=64,
        num_experts=4, moe_router="expert_choice", capacity_factor=1.0,
    )
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    gen = make_generate_fn(model, max_new_tokens=6)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (2, 8)), jnp.int32
    )
    out = gen(params, prompt, jax.random.PRNGKey(1))
    assert out.shape == (2, 14)
    assert (np.asarray(out[:, :8]) == np.asarray(prompt)).all()
