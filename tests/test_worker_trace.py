"""Fleet trace plane: clock alignment, worker-span collection, exemplars.

Host-pure halves first — the NTP-style ClockOffsetEstimator against
fake clocks with KNOWN skew and RTT asymmetry (the error must stay
inside the advertised rtt/2 bound), the TraceCollector's merge
contract (offset applied, out-of-order/duplicate frames, drop
accounting, restart reset), recorder span-loss accounting, the fleet
causality validator, exemplar exposition, and /flight federation.

Then THE acceptance e2e (slow+chaos, real worker processes): a
2-worker fleet with the trace plane on, SIGKILLed mid-decode, must
produce ONE validator-clean merged timeline — worker-side
prefill/decode spans under pid=worker-N lanes, the dead worker's
pre-crash spans and the survivor's spans sharing the original
trace_id, cross-process causality within the measured skew bound, and
a /metrics bucket exemplar that resolves into the merged trace.
"""

import json
import time

import numpy as np
import pytest

from ddp_practice_tpu.utils.metrics import MetricsRegistry
from ddp_practice_tpu.utils.trace import (
    ClockOffsetEstimator,
    TraceCollector,
    TraceRecorder,
)
from tools.check_traces import measured_skew, validate, validate_fleet


# --------------------------------------------------- clock offset (host-pure)
def test_offset_recovers_known_skew_within_bound():
    """Remote clock = local + 5s; asymmetric legs. The estimate must
    land within rtt/2 of the true offset — the classic NTP bound."""
    est = ClockOffsetEstimator()
    true_skew = 5.0
    # (t0, one-way out, one-way back): deliberately asymmetric
    for t0, out_s, back_s in [(10.0, 0.004, 0.001), (11.0, 0.0008, 0.0002),
                              (12.0, 0.002, 0.006)]:
        t_remote = t0 + out_s + true_skew
        est.add(t0, t_remote, t0 + out_s + back_s)
    assert est.n_samples == 3
    assert est.bound == pytest.approx(0.0005)   # best sample: 1ms rtt / 2
    assert abs(est.offset - true_skew) <= est.bound + 1e-12
    # min-RTT filtering: the 1ms-rtt sample wins over the 5/8ms ones
    assert est.min_rtt == pytest.approx(0.001)


def test_offset_min_rtt_preference_and_reset():
    est = ClockOffsetEstimator(max_samples=2)
    assert est.offset == 0.0 and est.bound is None
    assert est.add(0.0, 1.05, 0.1)        # rtt 0.1 -> first best
    assert est.add(1.0, 2.01, 1.02)       # rtt 0.02 -> new best
    assert not est.add(2.0, 3.5, 2.5)     # rtt 0.5 -> not best
    assert est.min_rtt == pytest.approx(0.02)
    assert est.total_samples == 3 and est.n_samples == 2  # capped
    est.reset()
    assert est.n_samples == 0 and est.offset == 0.0
    # a torn reading (t3 < t0) is refused
    assert not est.add(5.0, 5.0, 4.0) and est.n_samples == 0


# ------------------------------------------------ span-loss accounting
def test_recorder_counts_ring_drops_into_export_metadata():
    reg = MetricsRegistry()
    rec = TraceRecorder(max_events=4, clock=lambda: 0.0,
                        drop_counter=reg.counter(
                            "trace_events_dropped_total"))
    for i in range(10):
        rec.record_span(f"s{i}", float(i), float(i) + 0.5)
    assert rec.dropped == 6
    assert reg.counter("trace_events_dropped_total").value == 6
    out = rec.to_chrome_trace()
    assert out["metadata"]["trace_events_dropped"] == 6
    rec.count_external_drops(3)
    assert rec.to_chrome_trace()["metadata"]["trace_events_dropped"] == 9
    # a loss-free recorder exports WITHOUT the metadata key (existing
    # artifacts stay byte-identical)
    clean = TraceRecorder(clock=lambda: 0.0)
    clean.record_span("a", 0.0, 1.0)
    assert "metadata" not in clean.to_chrome_trace()


# -------------------------------------------------- collector (host-pure)
def _trace_frame(seq, events, dropped=0):
    return {"kind": "trace", "seq": seq, "events": events,
            "dropped": dropped}


def test_collector_merges_with_offset_and_dedups():
    reg = MetricsRegistry()
    fleet = TraceRecorder(clock=lambda: 0.0)
    col = TraceCollector(fleet, registry=reg)
    col.label_worker(0, 2)
    # worker clock runs 5s ahead; eager sample with 1ms rtt
    col.add_clock_sample(0, 10.0, 15.0005, 10.001)
    span = {"kind": "span", "name": "prefill", "t0": 15.1, "t1": 15.2,
            "pid": 0, "tid": 1, "trace_id": "r1"}
    inst = {"kind": "instant", "name": "shed", "t": 15.3, "pid": 0,
            "tid": 0}
    asy = {"kind": "async", "name": "request", "t0": 15.0, "t1": 15.4,
           "pid": 0, "trace_id": "r1"}
    # out of order, then duplicate
    assert col.ingest(0, _trace_frame(2, [inst, asy], dropped=2)) == 2
    assert col.ingest(0, _trace_frame(1, [span])) == 1
    assert col.ingest(0, _trace_frame(1, [span])) == 0
    # frames counts APPLIED frames; the duplicate is booked separately
    assert col.duplicates == 1 and col.frames == 2 and col.events == 3
    # worker-reported drops fold into fleet loss accounting
    assert fleet.dropped == 2
    assert reg.counter("trace_events_dropped_total").value == 2
    ev = fleet.to_chrome_trace()["traceEvents"]
    pre = [e for e in ev if e.get("name") == "prefill"]
    # merged timestamps are shifted into the LOCAL clock domain
    assert pre and abs(pre[0]["ts"] - 10.1e6) < 1e3
    names = {e["args"]["name"] for e in ev
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert "worker-0" in names
    # validator-clean (the async pair, the labelled pid, the instant)
    assert validate(fleet.to_chrome_trace()) == []


def test_collector_restart_resets_seq_and_offset():
    fleet = TraceRecorder(clock=lambda: 0.0)
    col = TraceCollector(fleet)
    col.label_worker(1, 1)
    col.add_clock_sample(1, 0.0, 100.0, 0.01)
    assert col.offset(1) != 0.0
    span = {"kind": "span", "name": "x", "t0": 100.0, "t1": 100.1,
            "pid": 1, "tid": 0}
    assert col.ingest(1, _trace_frame(5, [span])) == 1
    col.on_worker_restart(1)
    assert col.offset(1) == 0.0            # new incarnation, new clock
    # the same seq from the NEW incarnation is not a duplicate
    assert col.ingest(1, _trace_frame(5, [span])) == 1


def test_collector_worker_label_wins_over_replica_meta():
    fleet = TraceRecorder(clock=lambda: 0.0)
    col = TraceCollector(fleet)
    col.label_worker(0, 1)
    meta = {"kind": "meta", "meta": "process_name", "pid": 0,
            "name": "replica0"}
    col.ingest(0, _trace_frame(1, [meta]))
    assert fleet._process_names[0] == "worker-0"
    # clock_offset instants stamp the skew model into the timeline
    col.add_clock_sample(0, 0.0, 0.5, 0.002)
    ev = fleet.to_chrome_trace()["traceEvents"]
    off = [e for e in ev if e.get("name") == "clock_offset"]
    assert off and off[0]["args"]["bound_s"] == pytest.approx(0.001)


# ------------------------------------------------ fleet validator (host-pure)
def _mk_fleet_trace(dispatch_ts_us, queued_ts_us, bound_s=0.001):
    events = [
        {"name": "process_name", "ph": "M", "pid": -1, "tid": 0,
         "args": {"name": "router"}},
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "worker-0"}},
        {"name": "clock_offset", "ph": "i", "s": "t", "ts": 0.0,
         "pid": 0, "tid": 0,
         "args": {"offset_s": 0.0, "bound_s": bound_s, "rtt_s": 0.002}},
        {"name": "dispatch", "ph": "i", "s": "t", "ts": dispatch_ts_us,
         "pid": -1, "tid": 0,
         "args": {"replica": 0, "trace_id": "r1"}},
        {"name": "queued", "ph": "b", "cat": "request", "id": "r1",
         "ts": queued_ts_us, "pid": 0, "tid": 0},
        {"name": "queued", "ph": "e", "cat": "request", "id": "r1",
         "ts": queued_ts_us + 10, "pid": 0, "tid": 0},
    ]
    return {"traceEvents": events}


def test_fleet_causality_within_bound_passes():
    # queued starts 500us BEFORE dispatch; bound 1ms -> tolerated skew
    t = _mk_fleet_trace(dispatch_ts_us=10_000, queued_ts_us=9_500)
    assert validate_fleet(t) == []
    assert measured_skew(t) == {0: 0.001}


def test_fleet_causality_violation_fails():
    # queued 5ms before dispatch >> the 1ms stamped bound
    t = _mk_fleet_trace(dispatch_ts_us=10_000, queued_ts_us=5_000)
    errs = validate_fleet(t)
    assert len(errs) == 1 and "causality" in errs[0]
    # an explicit looser --skew-s overrides the stamped model
    assert validate_fleet(t, skew_s=0.01) == []


def test_fleet_validator_tolerates_truncated_worker_stream():
    # dispatch with NO worker-side spans at all (killed before any
    # frame was pushed): not an error
    t = _mk_fleet_trace(dispatch_ts_us=10_000, queued_ts_us=9_500)
    t["traceEvents"] = [e for e in t["traceEvents"]
                        if e.get("name") != "queued"]
    assert validate_fleet(t) == []


def test_measured_skew_keeps_worst_bound_per_pid():
    # events merged EARLY rode the cruder offset: the tolerance must be
    # the worst bound ever in effect, not the final tightest one
    t = _mk_fleet_trace(dispatch_ts_us=10_000, queued_ts_us=9_500)
    t["traceEvents"].insert(3, {
        "name": "clock_offset", "ph": "i", "s": "t", "ts": 1.0,
        "pid": 0, "tid": 0, "args": {"offset_s": 0.0, "bound_s": 0.02},
    })
    assert measured_skew(t)[0] == 0.02


# ------------------------------------------------------ exemplars (host-pure)
def test_histogram_exemplar_buckets_render_openmetrics():
    reg = MetricsRegistry()
    h = reg.histogram("serve_ttft_s")
    h.observe(0.008, exemplar="r7")
    h.observe(0.3, exemplar='r"9\\x')      # escaping
    h.observe(0.009)                        # no exemplar: bucket counted
    text = reg.render_text()
    assert ('serve_ttft_s_bucket{le="0.01"} 2 '
            '# {trace_id="r7"} 0.008') in text
    assert ('serve_ttft_s_bucket{le="0.5"} 3 '
            '# {trace_id="r\\"9\\\\x"} 0.3') in text
    assert 'serve_ttft_s_bucket{le="+Inf"} 3' in text
    # byte-stable: same registry state, same bytes
    assert reg.render_text() == text
    assert h.exemplar_for(99) == ('r"9\\x', 0.3)


def test_histogram_without_exemplars_renders_as_before():
    reg = MetricsRegistry()
    reg.histogram("h").observe(1.0)
    text = reg.render_text()
    assert "_bucket" not in text and "# {" not in text


def test_completion_trace_id_feeds_exemplars_end_to_end():
    """Scheduler -> ServeMetrics -> /metrics text: the p99 bucket's
    exemplar names the slow request's trace_id."""
    from ddp_practice_tpu.serve.metrics import ServeMetrics
    from ddp_practice_tpu.serve.scheduler import Completion

    reg = MetricsRegistry()
    m = ServeMetrics(reg)
    for i, ttft in enumerate([0.004, 0.005, 0.9]):
        m.on_complete(Completion(
            rid=i, tokens=[1], status="eos", arrival=0.0, finish=1.0,
            ttft=ttft, tpot=0.001, trace_id=f"r{i}",
        ), None)
    assert m.ttft.exemplar_for(99) == ("r2", 0.9)
    assert '# {trace_id="r2"} 0.9' in reg.render_text()


def test_relabel_metric_line_preserves_exemplar_section():
    from ddp_practice_tpu.utils.telemetry import _relabel_metric_line

    line = 'serve_ttft_s_bucket{le="0.01"} 2 # {trace_id="r7"} 0.008'
    out = _relabel_metric_line(line, 'worker="1"')
    assert out == ('serve_ttft_s_bucket{worker="1",le="0.01"} 2 '
                   '# {trace_id="r7"} 0.008')
    assert _relabel_metric_line('x_total 3', 'worker="0"') \
        == 'x_total{worker="0"} 3'


def test_flight_stats_exemplars_and_samples():
    from ddp_practice_tpu.serve.scheduler import Completion
    from ddp_practice_tpu.utils.telemetry import FlightStats

    fs = FlightStats()
    for i, ttft in enumerate([0.01, 0.02, 0.5]):
        fs.on_completion(Completion(
            rid=i, tokens=[1, 2], status="eos", arrival=0.0,
            finish=1.0, ttft=ttft, tpot=0.001,
            flight={"queue_s": 0.001, "prefill_s": 0.002,
                    "decode_s": 0.003, "stall_s": 0.0},
            trace_id=f"r{i}",
        ))
    rep = fs.report()
    assert rep["exemplars"]["ttft_p99"]["trace_id"] == "r2"
    assert rep["samples"]["ttft_s"] == [0.01, 0.02, 0.5]
    assert rep["samples"]["queue_s"] == [0.001] * 3


# ------------------------------------------------- /flight federation
def test_scrape_federator_pools_flight_samples():
    from ddp_practice_tpu.serve.scheduler import Completion
    from ddp_practice_tpu.utils.metrics import percentile_summary
    from ddp_practice_tpu.utils.telemetry import (
        FlightStats,
        ScrapeFederator,
        TelemetryServer,
    )

    stats, servers = [], []
    vals = [[0.01, 0.02], [0.5, 0.6, 0.7]]
    try:
        for wvals in vals:
            fs = FlightStats()
            for i, v in enumerate(wvals):
                fs.on_completion(Completion(
                    rid=i, tokens=[1], status="eos", arrival=0.0,
                    finish=1.0, ttft=v, tpot=None, trace_id=f"t{v}",
                ))
            srv = TelemetryServer(flight_fn=fs.report, port=0)
            stats.append(fs)
            servers.append(srv)
        targets = {
            i: {"host": "127.0.0.1", "port": s.port, "up": True,
                "pid": 1, "state": "running", "restarts": 0,
                "heartbeat_age_s": 0.0}
            for i, s in enumerate(servers)
        }
        fed = ScrapeFederator(lambda: targets)
        rolled = fed.flight()
        pooled = [v for w in vals for v in w]
        want = percentile_summary(pooled)
        assert rolled["fleet"]["ttft_s"] == want
        assert set(rolled["workers"]) == {"0", "1"}
        # worst exemplar anywhere wins the fleet slot
        assert rolled["fleet"]["exemplars"]["ttft_p99"]["trace_id"] \
            == "t0.7"
        # a dead worker is absent, not fatal
        targets[1]["up"] = False
        rolled = fed.flight()
        assert set(rolled["workers"]) == {"0"}
    finally:
        for s in servers:
            s.close()


# --------------------------------------------------------- THE acceptance e2e
MODEL_KW = {"vocab_size": 64, "max_len": 128, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 128, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}


def _trace(n=8, seed=5):
    rng = np.random.default_rng(seed)
    # LONG decode budgets (~20+ bursts): the fleet must stay busy for
    # seconds, because on a 1-core box the monitoring parent can be
    # starved off-CPU long enough for a short workload to drain
    # entirely between its steps — the kill needs a wide-open window
    return [{
        "rid": i,
        "prompt": rng.integers(1, 64, int(rng.integers(3, 9))).tolist(),
        "max_new_tokens": int(rng.integers(80, 101)),
    } for i in range(n)]


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_fleet_produces_one_validator_clean_merged_timeline():
    """ISSUE 8 acceptance: 2 REAL worker processes with the trace plane
    on, worker 0 SIGKILLed mid-decode -> zero lost; the merged timeline
    validates clean in fleet mode; a migrated request's pre-crash spans
    (dead worker lane) and post-failover spans (survivor lane) carry
    the ORIGINAL trace_id; /metrics bucket exemplars resolve into the
    merged trace; the federated /flight rolls up fleet percentiles."""
    import http.client
    import re

    from ddp_practice_tpu.serve.scheduler import Request
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_federated_server,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec

    def attempt():
        trace = _trace(n=6, seed=5)
        tracer = TraceRecorder()
        spec = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW,
                          max_queue=64, trace=True)
        router, sup, handles = make_fleet_router(
            spec, 2, tracer=tracer,
            sup_config=SupervisorConfig(restart_base_s=0.25,
                                        restart_budget=5,
                                        ready_timeout_s=300.0),
        )
        col = router.trace_collector
        fed = server = None
        try:
            assert col is not None
            # eager clock measurement happened at build, on an idle
            # fleet: both workers carry a measured (tight) skew bound
            for h in handles:
                assert col.skew_bound(h.id) is not None
                assert col.skew_bound(h.id) < 0.05
            for t in trace:
                router.submit(Request(**t))

            # kill gate: worker 0 is busy RIGHT NOW (a direct ping —
            # immune to the parent being starved off the streamed
            # snapshots) AND its spans have already reached the
            # collector (so the dead lane provably has pre-crash
            # events to link)
            def victim_busy():
                w = sup.worker(0)
                if w is None:
                    return False
                try:
                    st = w.client.call("ping", timeout_s=2.0)["stats"]
                    return st["active"] > 0
                except Exception:
                    return False

            deadline = time.monotonic() + 60
            while not (victim_busy()
                       and col.events_by_worker.get(0, 0) >= 2):
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            sup.kill(0, "SIGKILL")
            comps = router.run_until_idle()
            # ---- zero lost, all terminal
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            # ---- ONE validator-clean merged timeline, fleet mode
            chrome = tracer.to_chrome_trace()
            assert validate(chrome) == []
            assert validate_fleet(chrome) == []
            ev = chrome["traceEvents"]
            # worker-side spans landed under BOTH worker lanes
            lanes = {e["args"]["name"] for e in ev
                     if e["ph"] == "M" and e["name"] == "process_name"}
            assert {"worker-0", "worker-1", "router"} <= lanes
            for pid in (0, 1):
                assert any(e.get("ph") == "B" and e.get("pid") == pid
                           and e["name"] in ("prefill", "decode_burst")
                           for e in ev), f"no engine spans on pid {pid}"
            # ---- the one-timeline contract: SOME migrated request has
            # pre-crash spans on the dead worker AND survivor spans,
            # all under the original trace_id (a rid still queued at
            # kill time legitimately left no spans behind)
            def span_pids(tid):
                return {e["pid"] for e in ev
                        if ((e.get("args") or {}).get("trace_id") == tid
                            or e.get("id") == tid)
                        and e.get("ph") in ("B", "b", "i")}

            linked = [rid for rid in migrated
                      if 0 in span_pids(f"r{rid}")
                      and 1 in span_pids(f"r{rid}")]
            assert linked, (
                f"no migrated request links both worker lanes: "
                f"{[(rid, sorted(span_pids(f'r{rid}'), key=str)) for rid in migrated]}"
            )
            # ---- exemplars: the survivor's /metrics p99 TTFT bucket
            # names a trace_id present in the merged timeline
            ids_in_trace = set()
            for e in ev:
                a = e.get("args") or {}
                if "trace_id" in a:
                    ids_in_trace.add(a["trace_id"])
                if e.get("id") is not None:
                    ids_in_trace.add(e["id"])
            w1 = sup.worker(1)
            conn = http.client.HTTPConnection(
                "127.0.0.1", w1.telemetry_port, timeout=5.0)
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
            conn.close()
            exemplars = re.findall(
                r'serve_ttft_s_bucket\{le="[^"]+"\} \d+ '
                r'# \{trace_id="([^"]+)"\}', text)
            assert exemplars, "no bucket exemplars in /metrics"
            assert all(tid in ids_in_trace for tid in exemplars), (
                exemplars, sorted(ids_in_trace))
            # ---- federated /flight: fleet percentiles over pooled
            # worker samples
            fed, server = make_federated_server(sup, handles)
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=5.0)
            conn.request("GET", "/flight")
            flight = json.loads(conn.getresponse().read().decode())
            conn.close()
            assert flight["fleet"]["window"] >= len(trace) - len(migrated)
            assert flight["fleet"]["ttft_s"]["p99"] > 0
        finally:
            if server is not None:
                server.close()
            sup.stop()

    # one retry for the documented XLA-CPU near-tie class
    for i in range(2):
        try:
            return attempt()
        except AssertionError:
            if i == 1:
                raise
