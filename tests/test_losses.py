"""Loss/metric op tests: known-value cross-entropy, weighted counts."""

import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.ops import accuracy_counts, cross_entropy


def test_cross_entropy_uniform_logits():
    logits = jnp.zeros((4, 10))
    labels = jnp.asarray([0, 3, 7, 9])
    np.testing.assert_allclose(
        float(cross_entropy(logits, labels)), np.log(10.0), rtol=1e-6
    )


def test_cross_entropy_confident_correct():
    logits = jnp.asarray([[100.0, 0.0], [0.0, 100.0]])
    labels = jnp.asarray([0, 1])
    assert float(cross_entropy(logits, labels)) < 1e-6


def test_cross_entropy_weighted_ignores_padding():
    logits = jnp.asarray([[10.0, 0.0], [0.0, 10.0], [-50.0, 50.0]])
    labels = jnp.asarray([0, 1, 0])  # third is "wrong" but weight 0
    w = jnp.asarray([1.0, 1.0, 0.0])
    assert float(cross_entropy(logits, labels, weight=w)) < 1e-3


def test_accuracy_counts_weighted():
    logits = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0], [1.0, 0.0]])
    labels = jnp.asarray([0, 1, 1, 0])
    w = jnp.asarray([1.0, 1.0, 1.0, 0.0])  # last (correct) sample is padding
    correct, total = accuracy_counts(logits, labels, weight=w)
    assert float(correct) == 2.0
    assert float(total) == 3.0


def test_label_smoothing_increases_loss_on_confident():
    logits = jnp.asarray([[100.0, 0.0]])
    labels = jnp.asarray([0])
    plain = float(cross_entropy(logits, labels))
    smoothed = float(cross_entropy(logits, labels, label_smoothing=0.1))
    assert smoothed > plain
