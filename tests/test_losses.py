"""Loss/metric op tests: known-value cross-entropy, weighted counts."""

import jax.numpy as jnp
import pytest
import numpy as np

from ddp_practice_tpu.ops import accuracy_counts, cross_entropy


def test_cross_entropy_uniform_logits():
    logits = jnp.zeros((4, 10))
    labels = jnp.asarray([0, 3, 7, 9])
    np.testing.assert_allclose(
        float(cross_entropy(logits, labels)), np.log(10.0), rtol=1e-6
    )


def test_cross_entropy_confident_correct():
    logits = jnp.asarray([[100.0, 0.0], [0.0, 100.0]])
    labels = jnp.asarray([0, 1])
    assert float(cross_entropy(logits, labels)) < 1e-6


@pytest.mark.fast
def test_cross_entropy_weighted_ignores_padding():
    logits = jnp.asarray([[10.0, 0.0], [0.0, 10.0], [-50.0, 50.0]])
    labels = jnp.asarray([0, 1, 0])  # third is "wrong" but weight 0
    w = jnp.asarray([1.0, 1.0, 0.0])
    assert float(cross_entropy(logits, labels, weight=w)) < 1e-3


@pytest.mark.fast
def test_accuracy_counts_weighted():
    logits = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0], [1.0, 0.0]])
    labels = jnp.asarray([0, 1, 1, 0])
    w = jnp.asarray([1.0, 1.0, 1.0, 0.0])  # last (correct) sample is padding
    correct, total = accuracy_counts(logits, labels, weight=w)
    assert float(correct) == 2.0
    assert float(total) == 3.0


def test_label_smoothing_increases_loss_on_confident():
    logits = jnp.asarray([[100.0, 0.0]])
    labels = jnp.asarray([0])
    plain = float(cross_entropy(logits, labels))
    smoothed = float(cross_entropy(logits, labels, label_smoothing=0.1))
    assert smoothed > plain


def test_cross_entropy_grad_matches_logsoftmax_autodiff():
    """The hand-written _nll backward (round 4: closed-form
    softmax - y_smooth, no max/gather-VJP bookkeeping passes) must match
    autodiff of a plain log-softmax cross-entropy — with and without
    label smoothing and padding weights."""
    import jax

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((4, 9, 31)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 31, (4, 9)), jnp.int32)
    weight = jnp.asarray(rng.integers(0, 2, (4, 9)), jnp.float32)

    def reference(logits, labels, weight, ls):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        v = logits.shape[-1]
        y = (1.0 - ls) * jax.nn.one_hot(labels, v) + ls / v
        nll = -(y * logp).sum(-1)
        if weight is None:
            return nll.mean()
        return (nll * weight).sum() / jnp.maximum(weight.sum(), 1.0)

    for ls in (0.0, 0.1):
        for w in (None, weight):
            g_ours = jax.grad(
                lambda t: cross_entropy(
                    t, labels, weight=w, label_smoothing=ls
                )
            )(logits)
            g_ref = jax.grad(
                lambda t: reference(t, labels, w, ls)
            )(logits)
            np.testing.assert_allclose(
                np.asarray(g_ours), np.asarray(g_ref),
                rtol=1e-5, atol=1e-6,
            )
