"""Streaming delivery across REAL worker processes and REAL signals.

The ISSUE-10 acceptance teeth: chunks ride the worker push stream
(inside `pub` frames, atomically with the inflight salvage point), the
router splices them into per-request TokenStreams, and a SIGKILL
mid-stream produces a `resumed` marker — never a duplicated and never
a missing token. The host-pure halves (dedup cursor, typed ends,
check_stream) live in tests/test_zstream.py; this file proves the
same contract against actual process death, plus the graceful-SIGTERM
drain (satellite: a draining worker finishes its in-flight streams
with NO resume marker while refusing new submits).

Everything spawns real workers (~15 s each on this one-core image):
all `slow`, signal-delivering tests also `chaos`.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ddp_practice_tpu.serve.engine import EngineConfig
from ddp_practice_tpu.serve.scheduler import Request, Scheduler
from ddp_practice_tpu.serve.supervisor import (
    SupervisorConfig,
    live_worker_pids,
    make_fleet_router,
)
from ddp_practice_tpu.serve.worker import WorkerSpec, build_model
from ddp_practice_tpu.utils.telemetry import TelemetryExporter

pytestmark = pytest.mark.slow

MODEL_KW = {"vocab_size": 64, "max_len": 64, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 64, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}
SPEC = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW, max_queue=64)
SUP_CFG = SupervisorConfig(restart_base_s=0.25, restart_budget=5,
                           ready_timeout_s=300.0)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace(n=6, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = int(rng.integers(3, 9))
        out.append({
            "rid": i,
            "prompt": rng.integers(1, 64, plen).tolist(),
            "max_new_tokens": int(rng.integers(5, 9)),
        })
    return out


def _expected_tokens(trace):
    """Fault-free greedy oracle: one in-process scheduler, same model."""
    model, params = build_model(MODEL_KW)
    eng_kw = dict(ENGINE_KW)
    eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
    from ddp_practice_tpu.serve.engine import SlotEngine

    engine = SlotEngine(model, params, EngineConfig(**eng_kw))
    sched = Scheduler(engine, max_queue=64)
    for t in trace:
        sched.submit(Request(**t))
    comps = sched.run_until_idle()
    assert all(c.status == "length" for c in comps)
    return {c.rid: list(c.tokens) for c in comps}


def _tolerate_load_flake(attempt, tries=2):
    for i in range(tries):
        try:
            return attempt()
        except AssertionError:
            if i == tries - 1:
                raise


def _recount(stream):
    """Consumer-side recount, independent of the router's cursors:
    (dupes, gaps) over the delivered token offsets."""
    dupes = gaps = delivered = 0
    for ev in stream.events:
        if ev.kind != "tokens" or not ev.tokens:
            continue
        if ev.start < delivered:
            dupes += delivered - ev.start
        elif ev.start > delivered:
            gaps += ev.start - delivered
        delivered = ev.start + len(ev.tokens)
    return dupes, gaps


# --------------------------------------------- THE acceptance: SIGKILL
@pytest.mark.chaos
def test_sigkill_mid_stream_exactly_once(tmp_path):
    """SIGKILL one of two workers while its streams are mid-flight:
    every stream's concatenation is token-identical to the fault-free
    greedy oracle, seq is contiguous, the recounted duplicate/missing
    token totals are zero, resumed markers carry the ORIGINAL trace_id,
    and tools/check_stream.py passes the run's telemetry (and fails a
    corrupted copy)."""

    def attempt():
        trace = _trace(n=6, seed=5)
        expected = _expected_tokens(trace)
        tpath = str(tmp_path / "stream_run.jsonl")
        exporter = TelemetryExporter(tpath, start=False)
        router, sup, handles = make_fleet_router(
            SPEC, 2, sup_config=SUP_CFG, telemetry=exporter
        )
        try:
            for t in trace:
                router.submit(Request(**t))
            # mid-STREAM, observably: worker 0 holds in-flight work AND
            # some consumer stream has already delivered tokens
            deadline = time.monotonic() + 60
            while not (any(st["tokens"]
                           for st in handles[0].outstanding.values())
                       and any(s.delivered
                               for s in router.streams.values())):
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            sup.kill(0, "SIGKILL")                 # the real thing
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            for rid, want in expected.items():
                c = by_rid[rid]
                st = router.stream(rid)
                assert c.tokens == want, f"rid {rid} diverged"
                # the CONSUMER's spliced view equals the oracle too
                assert st.tokens() == want, f"stream {rid} diverged"
                assert st.closed and st.status == "length"
                assert [ev.seq for ev in st.events] \
                    == list(range(len(st.events)))
                dupes, gaps = _recount(st)
                assert dupes == 0 and gaps == 0
                # every event (incl. resumed) keeps the original
                # trace_id — the splice joins ONE timeline
                assert all(ev.trace_id == c.trace_id
                           for ev in st.events)
            resumed = [rid for rid in migrated
                       if any(ev.kind == "resumed"
                              for ev in router.stream(rid).events)]
            assert resumed == migrated, (
                "a migrated stream must carry its resume marker"
            )
            for rid in resumed:
                evs = [ev for ev in router.stream(rid).events
                       if ev.kind == "resumed"]
                assert all(ev.attrs["reason"] == "failover"
                           and ev.attrs["from_replica"] == 0
                           for ev in evs)
        finally:
            sup.stop()
            exporter.pump()
            exporter.close()
        # ---- the offline audit, both ways (the acceptance's last leg)
        r = subprocess.run(
            [sys.executable, "tools/check_stream.py", tpath],
            capture_output=True, text=True, cwd=ROOT, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [json.loads(x) for x in open(tpath) if x.strip()]
        out, dup = [], None
        for ln in lines:
            out.append(json.dumps(ln))
            if (dup is None and ln.get("kind") == "chunk"
                    and ln.get("event") == "tokens" and ln.get("n")):
                dup = json.dumps(ln)
                out.append(dup)
        assert dup is not None
        bad = tmp_path / "corrupt.jsonl"
        bad.write_text("\n".join(out) + "\n")
        r = subprocess.run(
            [sys.executable, "tools/check_stream.py", str(bad)],
            capture_output=True, text=True, cwd=ROOT, timeout=120,
        )
        assert r.returncode == 1 and "duplicate" in r.stdout

    _tolerate_load_flake(attempt)


# --------------------------------------- graceful drain: real SIGTERM
@pytest.mark.chaos
def test_sigterm_drain_finishes_streams_without_resume():
    """SIGTERM is the GRACEFUL edge: the worker flips to draining —
    refuses new submits at the door (typed, the router just routes
    around it) but finishes its in-flight requests, pushes their final
    chunks, and exits 0. The consumer must see those streams complete
    WITHOUT any resume marker (nothing migrated, nothing re-decoded),
    and later requests land on the survivor."""

    def attempt():
        trace = _trace(n=4, seed=11)
        expected = _expected_tokens(trace)
        router, sup, handles = make_fleet_router(
            SPEC, 2, sup_config=SUP_CFG
        )
        try:
            for t in trace:
                router.submit(Request(**t))
            deadline = time.monotonic() + 60
            while not any(st["tokens"]
                          for st in handles[0].outstanding.values()):
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            drained_rids = sorted(handles[0].outstanding)
            pid0 = sup.worker(0).pid
            os.kill(pid0, signal.SIGTERM)          # graceful, for real
            # new work while draining: refused at worker 0's door,
            # routed to the survivor, still terminal
            router.submit(Request(rid=100, prompt=[1, 2, 3, 4],
                                  max_new_tokens=5))
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace} | {100}
            assert all(c.status == "length" for c in by_rid.values())
            for rid, want in expected.items():
                assert by_rid[rid].tokens == want, f"rid {rid} diverged"
                assert router.stream(rid).tokens() == want
            # the drained worker FINISHED its streams in place: closed,
            # token-identical, and no resume marker anywhere on them
            for rid in drained_rids:
                st = router.stream(rid)
                assert st.closed and st.status == "length"
                kinds = [ev.kind for ev in st.events]
                assert "resumed" not in kinds, (
                    f"rid {rid} shows a resume — drain must finish "
                    f"in place, not migrate"
                )
                assert by_rid[rid].flight["failovers"] == 0
            # the refused request never ran on the draining worker
            assert 100 not in drained_rids
            st100 = router.stream(100)
            assert st100.closed and "resumed" not in [
                ev.kind for ev in st100.events]
            # the SIGTERMed process exited of its own accord (exit 0 —
            # drain complete), and is really gone
            deadline = time.monotonic() + 60
            while pid0 in live_worker_pids():
                assert time.monotonic() < deadline, (
                    "drained worker never exited"
                )
                time.sleep(0.1)
        finally:
            sup.stop()
        assert live_worker_pids() == []

    _tolerate_load_flake(attempt)
