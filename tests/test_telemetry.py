"""Live telemetry plane (utils/telemetry.py): streaming JSONL export,
HTTP scrape endpoints, straggler detection, and the metrics satellites.

The streaming contract under test is the one the exit-time trace dump
cannot give: every line is written whole and flushed, so a run killed
with SIGKILL still leaves a file that parses line by line (at worst one
truncated tail line) — pinned with a real subprocess and a real SIGKILL.
The HTTP side binds port 0 and is scraped through http.client, shutdown
included. All host-pure: no jax, no engines, fast.
"""

import http.client
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from ddp_practice_tpu.serve.scheduler import Completion
from ddp_practice_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    default_registry,
    labelled,
    percentile_summary,
    reset_label_guard,
    set_label_limit,
)
from ddp_practice_tpu.utils.telemetry import (
    FlightStats,
    StepAnomalyDetector,
    TelemetryExporter,
    TelemetryServer,
)
from ddp_practice_tpu.utils.trace import TraceRecorder, label_replica
from tools.check_traces import parse_stream_text, validate


def _completion(rid=0, status="length", ttft=0.2, tpot=0.01,
                queue_s=0.1, prefill_s=0.05, decode_s=0.3, stall_s=0.0):
    return Completion(
        rid=rid, tokens=[1, 2, 3], status=status, arrival=0.0,
        finish=queue_s + prefill_s + decode_s + stall_s,
        ttft=ttft, tpot=tpot,
        flight={"queue_s": queue_s, "prefill_s": prefill_s,
                "decode_s": decode_s, "stall_s": stall_s,
                "retries": 0, "failovers": 0},
    )


# --------------------------------------------------------------- exporter
def test_exporter_streams_jsonl_lines(tmp_path):
    path = str(tmp_path / "t.jsonl")
    reg = MetricsRegistry()
    reg.counter("requests").inc(7)
    exp = TelemetryExporter(path, registry=reg, clock=lambda: 2.5,
                            start=False)
    exp.emit("alert", event="trip", objective="error_rate")
    exp.on_completion(_completion(rid=3))
    exp.snapshot_now()
    exp.close()
    lines = [json.loads(ln) for ln in
             open(path).read().strip().split("\n")]
    kinds = [ln["kind"] for ln in lines]
    assert kinds[0] == "alert" and lines[0]["t"] == 2.5
    flight = lines[kinds.index("flight")]
    assert flight["rid"] == 3 and flight["queue_s"] == 0.1
    snap = lines[kinds.index("metrics")]
    assert snap["snapshot"]["requests"] == 7
    # close() writes a final snapshot + the drop count
    assert kinds[-1] == "telemetry_close" and lines[-1]["dropped"] == 0


def test_exporter_bounded_queue_drops_and_counts(tmp_path):
    path = str(tmp_path / "t.jsonl")
    reg = MetricsRegistry()
    exp = TelemetryExporter(path, registry=reg, max_queue=2, start=False)
    for i in range(5):  # no consumer running: 3 of 5 must drop
        exp.emit("flight", rid=i)
    assert exp.dropped == 3
    assert reg.counter("telemetry_dropped_total").value == 3
    exp.close()
    lines = [json.loads(ln) for ln in
             open(path).read().strip().split("\n")]
    flights = [ln for ln in lines if ln["kind"] == "flight"]
    assert [f["rid"] for f in flights] == [0, 1]  # oldest-first survive
    assert lines[-1]["dropped"] == 3


def test_exporter_background_thread_drains(tmp_path):
    path = str(tmp_path / "t.jsonl")
    exp = TelemetryExporter(path, snapshot_interval_s=0.0)  # start=True
    for i in range(50):
        exp.emit("flight", rid=i)
    exp.close()
    lines = [json.loads(ln) for ln in
             open(path).read().strip().split("\n")]
    assert sum(ln["kind"] == "flight" for ln in lines) == 50
    assert exp.dropped == 0


def test_trace_sink_stream_revalidates_as_chrome_trace(tmp_path):
    """Streamed span/async/instant/meta lines re-assemble into a
    validator-clean Chrome trace (tools/check_traces.py stream mode)."""
    path = str(tmp_path / "t.jsonl")
    exp = TelemetryExporter(path, start=False)
    t = {"now": 0.0}
    tr = TraceRecorder(clock=lambda: t["now"])
    label_replica(tr, 0, 2)  # labelled BEFORE attach: must be replayed
    exp.attach(tr)
    tr.record_span("prefill", 0.1, 0.2, pid=0, tid=1, trace_id="r1",
                   attrs={"bucket": 8})
    tr.record_async("request", 0.0, 0.5, trace_id="r1", pid=0)
    t["now"] = 0.3
    tr.instant("shed", pid=0, tid=0, rid=9)
    exp.close()
    trace, truncated, errors = parse_stream_text(open(path).read())
    assert not truncated and not errors
    assert validate(trace) == []
    by_ph = {}
    for ev in trace["traceEvents"]:
        by_ph.setdefault(ev["ph"], []).append(ev)
    assert [e["name"] for e in by_ph["X"]] == ["prefill"]
    assert by_ph["X"][0]["args"] == {"bucket": 8, "trace_id": "r1"}
    assert {e["ph"] for e in by_ph["b"] + by_ph["e"]} == {"b", "e"}
    assert by_ph["i"][0]["name"] == "shed"


def test_sigkill_leaves_line_parseable_file(tmp_path):
    """THE flush-on-crash pin: a writer process killed with SIGKILL
    mid-stream leaves a telemetry file every line of which (except at
    most a truncated tail) parses — the property the exit-time dump
    fundamentally lacks."""
    path = str(tmp_path / "killed.jsonl")
    script = f"""
import sys
sys.path.insert(0, {os.getcwd()!r})
from ddp_practice_tpu.utils.telemetry import TelemetryExporter
exp = TelemetryExporter({path!r}, snapshot_interval_s=0.0)
i = 0
print("ready", flush=True)
while True:
    exp.emit("flight", rid=i, payload="x" * 256)
    i += 1
"""
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=os.getcwd(),
    )
    try:
        assert proc.stdout.readline().strip() == b"ready"
        # let it stream for a moment, then kill it un-gracefully
        deadline = time.monotonic() + 5.0
        while (not os.path.exists(path) or os.path.getsize(path) < 4096) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
    raw = open(path).read()
    lines = raw.split("\n")
    while lines and not lines[-1].strip():
        lines.pop()
    assert len(lines) >= 10, "writer never got going"
    parsed = 0
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
            assert rec["kind"] == "flight"
            parsed += 1
        except json.JSONDecodeError:
            assert i == len(lines) - 1, \
                f"non-tail line {i} corrupt — flush-per-line is broken"
    assert parsed >= 10
    # and the offline tool accepts the same file
    trace, truncated, errors = parse_stream_text(raw)
    assert errors == []


# ------------------------------------------------------------- HTTP plane
def test_http_endpoints_scrape_and_clean_shutdown():
    reg = MetricsRegistry()
    reg.counter("serve_tokens_total").inc(42)
    reg.histogram("serve_ttft_s").observe(0.25)
    flight = FlightStats()
    flight.on_completion(_completion())
    health = {"states": {0: "healthy", 1: "degraded"}}
    srv = TelemetryServer(
        registry=reg, health_fn=lambda: health["states"],
        flight_fn=flight.report, port=0,
    )
    assert srv.port > 0  # ephemeral bind reported

    def get(p):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=5)
        conn.request("GET", p)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    status, body = get("/metrics")
    text = body.decode()
    assert status == 200
    assert "serve_tokens_total 42" in text
    assert 'serve_ttft_s{quantile="0.99"}' in text
    assert text == reg.render_text()  # byte-stable exposition

    status, body = get("/healthz")
    payload = json.loads(body)
    assert status == 200 and payload["status"] == "DEGRADED"
    assert payload["replicas"] == {"0": "healthy", "1": "degraded"}

    health["states"] = {0: "dead", 1: "dead"}
    status, body = get("/healthz")
    assert status == 503 and json.loads(body)["status"] == "DEAD"

    status, body = get("/flight")
    rep = json.loads(body)
    assert status == 200 and rep["window"] == 1
    assert rep["decode_s"]["p99"] == pytest.approx(0.3)

    assert get("/nope")[0] == 404

    srv.close()
    with pytest.raises(OSError):
        get("/metrics")  # nothing listening after close


# ------------------------------------------------- straggler detection
def test_step_anomaly_detector_flags_stragglers_only():
    det = StepAnomalyDetector(window=32, threshold=5.0, min_samples=8)
    flags = [det.observe(0.1 + 0.001 * (i % 3)) for i in range(16)]
    assert not any(flags)
    assert det.observe(0.5)        # 5x the median: straggler
    assert not det.observe(0.02)   # FAST step is not an anomaly
    assert det.anomalies == 1


def test_step_anomaly_detector_survives_constant_history():
    # FakeClock-flat history collapses MAD to 0; the relative floor
    # must keep microscopic jitter from flagging
    det = StepAnomalyDetector(min_samples=4)
    for _ in range(8):
        assert not det.observe(0.1)
    assert not det.observe(0.1001)
    assert det.observe(0.2)


# --------------------------------------------------- metrics satellites
def test_percentile_summary_is_the_histogram_math():
    xs = [0.5, 0.1, 0.9, 0.3, 0.7]
    s = percentile_summary(xs)
    h = Histogram.of(xs)
    assert s["p50"] == h.percentile(50)
    assert s["p99"] == h.percentile(99)
    assert s["mean"] == pytest.approx(h.mean)
    assert percentile_summary([]) == {
        "p50": 0.0, "p90": 0.0, "p99": 0.0, "mean": 0.0,
    }


@pytest.fixture
def label_guard():
    reset_label_guard()
    old = set_label_limit(3)
    yield
    set_label_limit(old)
    reset_label_guard()


def test_labelled_cardinality_guard(label_guard):
    ctr = default_registry().counter("metrics_label_overflow_total")
    base = ctr.value
    reg = MetricsRegistry()
    for rid in range(10):  # an unbounded label (request ids)
        reg.counter(labelled("sheds", reason=f"r{rid}")).inc()
    snap = reg.snapshot()
    named = [k for k in snap if k.startswith("sheds{")]
    # 3 distinct values + the shared overflow bucket — not 10
    assert len(named) == 4
    assert snap["sheds{reason=other}"] == 7
    assert ctr.value - base == 7
    # repeat values keep hitting their established bucket
    reg.counter(labelled("sheds", reason="r0")).inc()
    assert reg.snapshot()["sheds{reason=r0}"] == 2


def test_labelled_guard_does_not_touch_small_families(label_guard):
    assert labelled("m", replica=0) == "m{replica=0}"
    assert labelled("m", replica=1) == "m{replica=1}"
    assert labelled("m", replica=0) == "m{replica=0}"  # re-seen: stable
