"""Live telemetry egress: OTLP push pipeline, adaptive head rate, tenants.

Host-pure halves first — the incremental drain (each span in exactly
one batch, late spans parenting onto roots that shipped batches ago),
the OtlpPusher delivery machinery against a scripted fake transport
(batch identity, at-least-once retry of the SAME batch id, the bounded
pending queue) and its breaker under a FakeClock (death at
max_failures keeping one newest batch, FIXED-cadence half-open probes,
recovery closing the breaker), then the AdaptiveHeadRateController's
convergence contract (±20% of budget after a 4x traffic step, no rate
reversal inside its own hold window) and the per-tenant dimension
(head-rate overrides, tenant-labelled metrics behind the labelled()
cardinality guard).

Then the integration tiers: a real StubOtlpCollector over HTTP with
fault injection (ack-lost duplicates absorbed by batch-id dedup, a
mid-run collector outage survived with ZERO span loss — the ISSUE 12
completeness acceptance), and THE two-tenant chaos e2e (slow+chaos): a
2-worker fleet where tenant "acme" head-samples at 1.0 while
"free-tier" rides the 1% fleet default, worker 0 SIGKILLed mid-decode
— every fault-affected request from BOTH tenants must surface in the
kept timeline under its original trace_id, clean free-tier traffic
stays suppressed, clean acme traffic stays kept, and the merged trace
validates fleet-clean.
"""

import time

import numpy as np
import pytest

from ddp_practice_tpu.utils.metrics import (
    MetricsRegistry,
    default_registry,
    reset_label_guard,
)
from ddp_practice_tpu.utils.telemetry import OtlpPusher, StubOtlpCollector
from ddp_practice_tpu.utils.trace import (
    AdaptiveHeadRateController,
    TraceRecorder,
    TraceSampler,
    head_keep,
)
from tools.check_otlp import validate_otlp


class _Clk:
    """Minimal settable clock (same shape the trace tests use)."""

    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t


def _span_ids(export):
    return {
        sp["spanId"]
        for rs in export.get("resourceSpans", ())
        for ss in rs.get("scopeSpans", ())
        for sp in ss.get("spans", ())
    }


def _batch_id(export):
    for rs in export.get("resourceSpans", ()):
        for kv in rs.get("resource", {}).get("attributes", ()):
            if kv.get("key") == "ddp.push.batch_id":
                return kv.get("value", {}).get("stringValue")
    return None


def _record_wave(rec, rids, t0=0.0):
    """One request-shaped span group per rid (root async + child)."""
    for rid in rids:
        t = f"r{rid}"
        rec.record_async("request", t0, t0 + 0.1, trace_id=t, pid=0)
        rec.record_span("prefill", t0, t0 + 0.05, trace_id=t, pid=0,
                        tid=1)


# --------------------------------------------- incremental drain (host-pure)
def test_drain_otlp_each_span_in_exactly_one_batch():
    rec = TraceRecorder()
    _record_wave(rec, (1, 2))
    b1 = rec.drain_otlp()
    assert b1 is not None and validate_otlp(b1) == []
    assert rec.drain_otlp() is None        # high-water mark: nothing new
    rec.record_span("decode", 0.1, 0.2, trace_id="r1", pid=0, tid=1)
    _record_wave(rec, (3,), t0=0.2)
    b2 = rec.drain_otlp()
    s1, s2 = _span_ids(b1), _span_ids(b2)
    assert s1 and s2 and not (s1 & s2)     # disjoint batches
    # the union IS the exit-time export — nothing lost, nothing doubled
    assert s1 | s2 == _span_ids(rec.to_otlp())


def test_drain_otlp_late_spans_parent_onto_shipped_root():
    rec = TraceRecorder()
    _record_wave(rec, (1,))
    b1 = rec.drain_otlp()
    roots = [sp for rs in b1["resourceSpans"]
             for ss in rs["scopeSpans"] for sp in ss["spans"]
             if "parentSpanId" not in sp]
    assert [sp["name"] for sp in roots] == ["request"]
    root_sid = roots[0]["spanId"]
    # a span drained BATCHES after its root still parents onto it
    rec.record_span("decode", 0.2, 0.3, trace_id="r1", pid=0, tid=1)
    b2 = rec.drain_otlp()
    late = [sp for rs in b2["resourceSpans"]
            for ss in rs["scopeSpans"] for sp in ss["spans"]]
    assert [sp["name"] for sp in late] == ["decode"]
    assert late[0]["parentSpanId"] == root_sid


# ------------------------------------------------ pusher vs fake transport
class _Post:
    """Scripted transport: pops one scripted outcome per call (an
    Exception instance raises); an empty script answers True. Records
    (clock-time, batch_id) per call so tests can pin retry identity and
    probe cadence."""

    def __init__(self, clk, script=()):
        self.clk = clk
        self.script = list(script)
        self.calls = []

    def __call__(self, url, payload, timeout_s=None):
        self.calls.append((self.clk.now(), _batch_id(payload)))
        if self.script:
            r = self.script.pop(0)
            if isinstance(r, Exception):
                raise r
            return r
        return True


def _pusher(rec, post, clk, **kw):
    kw.setdefault("run_token", "tok")
    return OtlpPusher("http://collector:4318/v1/traces", rec, post=post,
                      clock=clk, start=False, **kw)


def test_pusher_batch_identity_and_bookkeeping():
    clk = _Clk()
    rec = TraceRecorder()
    post = _Post(clk)
    p = _pusher(rec, post, clk, registry=(reg := MetricsRegistry()))
    _record_wave(rec, (1, 2))
    assert p.pump(clk.now()) == 4          # 2 rids x (request + prefill)
    assert [bid for _, bid in post.calls] == ["tok-1"]
    assert p.batches_sent == 1 and p.spans_sent == 4
    assert p.pump(clk.now()) == 0          # nothing new: no empty POST
    assert len(post.calls) == 1
    _record_wave(rec, (3,))
    p.pump(clk.now())
    assert [bid for _, bid in post.calls] == ["tok-1", "tok-2"]
    snap = reg.snapshot()
    assert snap["otlp_batches_sent_total"] == 2
    assert snap["otlp_spans_sent_total"] == 6
    assert snap["otlp_endpoint_dead"] == 0


@pytest.mark.parametrize("failure", [False, RuntimeError("conn reset")])
def test_pusher_retries_the_same_batch_id(failure):
    """At-least-once: a failed (or raising) POST leaves the batch
    pending and the retry carries the IDENTICAL batch id — the dedup
    key the collector keeps first-writer-wins on. A fresh id here would
    be the drain re-emission bug the capture validator calls INVALID."""
    clk = _Clk()
    rec = TraceRecorder()
    post = _Post(clk, script=[failure, True])
    p = _pusher(rec, post, clk)
    _record_wave(rec, (1,))
    assert p.pump(clk.now()) == 0          # delivery failed
    assert p.post_failures == 1 and p.pending_batches == 1
    clk.t += 60.0                          # clear any backoff
    assert p.pump(clk.now()) == 2
    assert [bid for _, bid in post.calls] == ["tok-1", "tok-1"]
    assert p.pending_batches == 0 and p.batches_sent == 1


def test_pusher_backoff_gates_the_retry():
    clk = _Clk()
    rec = TraceRecorder()
    post = _Post(clk, script=[False])
    p = _pusher(rec, post, clk, base_s=0.5, max_s=30.0, seed=0)
    _record_wave(rec, (1,))
    p.pump(clk.now())
    assert len(post.calls) == 1
    clk.t += 0.01                          # inside the backoff window
    p.flush(clk.now())
    assert len(post.calls) == 1            # no hammer
    clk.t += 60.0
    p.flush(clk.now())
    assert len(post.calls) == 2


def test_pusher_bounded_queue_drops_oldest():
    clk = _Clk()
    rec = TraceRecorder()
    post = _Post(clk, script=[False] * 3)
    reg = MetricsRegistry()
    p = _pusher(rec, post, clk, max_pending=2, max_failures=100,
                registry=reg)
    for rid in (1, 2, 3):
        _record_wave(rec, (rid,))
        p.collect()
    assert p.pending_batches == 2          # bounded: serving never pays
    assert p.batches_dropped == 1
    assert reg.snapshot()["otlp_batches_dropped_total"] == 1
    # the OLDEST batch went; the survivors deliver in order
    post.script = []
    clk.t += 60.0
    p.flush(clk.now())
    assert [bid for _, bid in post.calls[-2:]] == ["tok-2", "tok-3"]


# ----------------------------------------------- breaker (FakeClock-driven)
def test_breaker_death_probe_cadence_and_recovery():
    clk = _Clk()
    rec = TraceRecorder()
    post = _Post(clk, script=[False] * 4)   # 3 to die + 1 failed probe
    reg = MetricsRegistry()
    p = _pusher(rec, post, clk, max_failures=3, base_s=0.5, max_s=4.0,
                probe_cooldown_s=30.0, seed=0, registry=reg)
    # three failed deliveries (clock stepped past each backoff) -> DEAD
    _record_wave(rec, (1,))
    for _ in range(3):
        clk.t += 60.0
        p.pump(clk.now())
    assert p.dead is True and p.failures == 3
    assert reg.snapshot()["otlp_endpoint_dead"] == 1
    assert p.pending_batches == 1          # one newest batch kept
    # while dead, collects keep ONLY the newest batch (probe payload)
    _record_wave(rec, (2,))
    p.collect()
    _record_wave(rec, (3,))
    p.collect()
    assert p.pending_batches == 1
    assert p.batches_dropped >= 2
    t_dead = clk.t
    n_calls = len(post.calls)
    # inside the cooldown: no probe
    clk.t = t_dead + 5.0
    p.flush(clk.now())
    assert len(post.calls) == n_calls
    # at the cooldown: exactly one probe; a failed probe re-arms the
    # FIXED cooldown (never exponential — probe cadence IS the
    # recovery-detection latency)
    clk.t = t_dead + 30.0
    p.flush(clk.now())
    assert len(post.calls) == n_calls + 1
    clk.t = t_dead + 59.0                  # 29s after the failed probe
    p.flush(clk.now())
    assert len(post.calls) == n_calls + 1
    clk.t = t_dead + 60.0
    p.flush(clk.now())                     # script exhausted: succeeds
    assert len(post.calls) == n_calls + 2
    assert post.calls[-1][0] - post.calls[-2][0] == 30.0
    # recovery: breaker closed, gauge cleared, the kept batch delivered
    assert p.dead is False and p.failures == 0
    assert reg.snapshot()["otlp_endpoint_dead"] == 0
    assert p.pending_batches == 0
    assert post.calls[-1][1] == "tok-3"    # the newest, older two died


# ---------------------------------------- real-HTTP collector integration
def test_collector_dedups_ack_lost_duplicate_end_to_end():
    """delivered-but-response-lost: the collector captured the batch
    but answered 500, so the pusher retries and the SAME batch id
    arrives twice — the receiver's dedup absorbs it, span-exactly-once
    after dedup."""
    col = StubOtlpCollector()
    rec = TraceRecorder()
    p = OtlpPusher(col.endpoint, rec, start=False, base_s=0.01,
                   max_s=0.02, run_token="e2e")
    try:
        _record_wave(rec, (1, 2))
        assert p.pump() == 4
        col.drop_response_next(1)
        _record_wave(rec, (3,))
        assert p.pump() == 0               # captured, ack lost
        deadline = time.monotonic() + 10.0
        while p.pending_batches and time.monotonic() < deadline:
            p.flush()                      # retry past the tiny backoff
            time.sleep(0.01)
        assert p.pending_batches == 0
        assert col.duplicates == 1
        assert col.span_ids() == _span_ids(rec.to_otlp())
        assert col.spans == p.spans_sent == 6
    finally:
        p.close()
        col.close()


def test_collector_outage_mid_run_loses_no_span():
    """ISSUE 12 completeness acceptance: the pusher runs THREADED while
    spans keep arriving and the collector goes through a hard outage
    (503s, nothing captured) plus an ack-lost round — after close(),
    the deduped capture holds EVERY kept span the recorder ever
    drained."""
    col = StubOtlpCollector()
    rec = TraceRecorder()
    p = OtlpPusher(col.endpoint, rec, interval_s=0.02, base_s=0.02,
                   max_s=0.05, max_failures=50, run_token="kill")
    try:
        for k in range(10):
            _record_wave(rec, (10 * k, 10 * k + 1), t0=0.1 * k)
            if k == 4:
                col.fail_next(3)           # mid-run outage
            if k == 7:
                col.drop_response_next(1)  # ack lost -> duplicate
            time.sleep(0.04)
        # the background thread must recover on its own (close honors
        # an armed backoff — it is not a license to hammer)
        deadline = time.monotonic() + 10.0
        while p.pending_batches and time.monotonic() < deadline:
            time.sleep(0.02)
        assert p.pending_batches == 0
    finally:
        p.close()                          # final best-effort flush
        col.close()
    assert p.post_failures >= 1            # the outage really happened
    assert col.span_ids() == _span_ids(rec.to_otlp())
    assert col.spans == 40                 # 20 rids x 2 spans, once each
    assert p.batches_dropped == 0


# ------------------------------------- adaptive head rate (FakeClock)
def _steered(ctl, rec, clk, arrival_sps, seconds):
    """Drive `seconds` 1s ticks: kept flow == arrival * current rate
    (the ~linear plant the controller assumes), one step() per tick."""
    for _ in range(seconds):
        clk.t += 1.0
        rec.spans_sampled += int(arrival_sps * ctl.rate)
        ctl.step()


def test_adaptive_converges_after_4x_step_without_oscillation():
    clk = _Clk()
    rec = TraceRecorder(clock=clk)
    rec.set_sampler(TraceSampler(1.0))
    pushed = []
    ctl = AdaptiveHeadRateController(
        rec, 150.0, clock=clk, interval_s=1.0, deadband=0.1,
        hold_s=2.0, apply_fn=pushed.append)
    ctl.step()                             # establishes the baseline
    _steered(ctl, rec, clk, 200.0, 6)      # base traffic: 200 sps
    assert abs(ctl.last_observed_sps - 150.0) <= 0.2 * 150.0
    base_changes = ctl.changes
    _steered(ctl, rec, clk, 800.0, 8)      # the 4x step
    # converged back inside ±20% of budget, and not by luck on the
    # last tick: the deadband held it there (no trailing change)
    assert abs(ctl.last_observed_sps - 150.0) <= 0.2 * 150.0
    assert ctl.rate_log[-1]["t"] < clk.t - 2.0
    assert ctl.changes > base_changes      # the step WAS corrected
    # no-oscillation contract: consecutive changes never inside one
    # hold window of each other (so a rate can never reverse there)
    ts = [c["t"] for c in ctl.rate_log]
    assert all(b - a >= ctl.hold_s for a, b in zip(ts, ts[1:]))
    # every change was pushed to the fleet and stamped in the timeline
    assert pushed == [c["rate"] for c in ctl.rate_log]
    assert ctl.recorder.sampler.rate == ctl.rate
    stamps = [e for e in rec.to_chrome_trace()["traceEvents"]
              if e.get("name") == "trace_rate"]
    assert len(stamps) == ctl.changes
    assert stamps[-1]["args"]["rate"] == ctl.rate


def test_adaptive_deadband_and_hold_prevent_churn():
    clk = _Clk()
    rec = TraceRecorder(clock=clk)
    rec.set_sampler(TraceSampler(1.0))
    ctl = AdaptiveHeadRateController(
        rec, 150.0, clock=clk, interval_s=1.0, deadband=0.1, hold_s=5.0)
    ctl.step()
    # on-budget flow (inside the deadband): zero changes, ever
    _steered(ctl, rec, clk, 155.0, 5)
    assert ctl.changes == 0
    # one off-budget correction, then the hold window pins the rate
    # even though the (simulated) flow keeps reading off-budget
    rec.spans_sampled += 600
    clk.t += 1.0
    ctl.step()
    assert ctl.changes == 1
    t_change = clk.t
    for _ in range(4):                     # 4s < hold_s
        rec.spans_sampled += 600
        clk.t += 1.0
        ctl.step()
    assert ctl.changes == 1 and clk.t - t_change < ctl.hold_s + 1.0


def test_adaptive_probes_upward_from_silence_and_clamps():
    clk = _Clk()
    rec = TraceRecorder(clock=clk)
    rec.set_sampler(TraceSampler(0.25))
    ctl = AdaptiveHeadRateController(
        rec, 150.0, clock=clk, interval_s=1.0, hold_s=0.0,
        max_rate=1.0)
    ctl.step()
    clk.t += 1.0
    ctl.step()                             # observed 0: doubled, not /0
    assert ctl.rate == 0.5
    clk.t += 1.0
    ctl.step()
    assert ctl.rate == 1.0                 # clamped at max_rate
    clk.t += 1.0
    assert ctl.step() is None              # already at the clamp
    with pytest.raises(ValueError):
        AdaptiveHeadRateController(rec, 0.0)
    # a failing fleet push must not take the control loop down
    ctl2 = AdaptiveHeadRateController(
        rec, 150.0, clock=clk, interval_s=1.0, hold_s=0.0,
        apply_fn=lambda r: (_ for _ in ()).throw(RuntimeError("rpc")))
    ctl2.step()
    rec.spans_sampled += 600
    clk.t += 1.0
    assert ctl2.step() is not None         # changed despite the raise


# ------------------------------------------------- per-tenant dimension
def test_tenant_head_rate_overrides_and_tenant_blind_tail():
    s = TraceSampler(0.01, tenant_rates={"acme": 1.0, "muted": 0.0})
    assert s.rate_for("acme") == 1.0
    assert s.rate_for("muted") == 0.0
    assert s.rate_for("unknown") == 0.01
    assert s.rate_for(None) == 0.01
    ids = [f"r{i}" for i in range(50)]
    assert all(s.sampled(t, "acme") for t in ids)
    assert not any(s.sampled(t, "muted") for t in ids)
    assert [s.sampled(t, "unknown") for t in ids] \
        == [head_keep(t, 0.01) for t in ids]
    # the recorder honors the tenant at admission...
    rec = TraceRecorder()
    rec.set_sampler(TraceSampler(0.0, tenant_rates={"acme": 1.0}))
    assert rec.begin_trace("rA", tenant="acme") is True
    assert rec.begin_trace("rB", tenant="free") is False
    # ...but tail keep is tenant-BLIND: a muted tenant's fault still
    # promotes its staged trace (anomalies outrank sampling budgets)
    assert rec.finish_trace("rB", status="error", latency_s=0.1) is True
    assert rec.sampling_meta()["tenant_rates"] == {"acme": 1.0}


def _completion(rid, *, tenant, status="eos", sampled=True):
    from ddp_practice_tpu.serve.scheduler import Completion

    return Completion(
        rid=rid, tokens=[1, 2, 3], status=status, arrival=0.0,
        finish=1.0, ttft=0.05, tpot=0.01, trace_id=f"r{rid}",
        trace_sampled=sampled, tenant=tenant,
    )


def test_tenant_labels_ride_completions_into_metrics():
    from ddp_practice_tpu.serve.metrics import RouterMetrics, ServeMetrics

    reset_label_guard()
    try:
        m = ServeMetrics()
        m.on_complete(_completion(1, tenant="acme"), None)
        m.on_complete(_completion(2, tenant="acme", status="shed"), None)
        m.on_complete(_completion(3, tenant=None), None)   # untenanted
        snap = m.report()
        assert snap[
            "serve_tenant_requests_total{status=eos,tenant=acme}"] == 1
        assert snap[
            "serve_tenant_requests_total{status=shed,tenant=acme}"] == 1
        assert snap["serve_tenant_tokens_total{tenant=acme}"] == 6
        assert not any("tenant=None" in k for k in snap)
        rm = RouterMetrics()
        rm.on_finalize(_completion(4, tenant="free"))
        rsnap = rm.report()
        assert rsnap[
            "serve_router_tenant_requests_total{status=eos,tenant=free}"
        ] == 1
        assert rsnap["serve_router_tenant_tokens_total{tenant=free}"] == 3
        assert any(k.startswith("serve_router_tenant_ttft_s{tenant=free}")
                   for k in rsnap)
    finally:
        reset_label_guard()


def test_tenant_label_cardinality_overflow_bounds_the_registry():
    """An adversarial flood of tenant ids must NOT grow the registry
    (and every scrape) without bound: past the per-(metric, label) cap
    the guard folds new values into tenant="other" and counts the
    overflow in the default registry."""
    from ddp_practice_tpu.serve.metrics import ServeMetrics
    from ddp_practice_tpu.utils.metrics import _LABEL_LIMIT

    reset_label_guard()
    before = default_registry().snapshot().get(
        "metrics_label_overflow_total", 0)
    try:
        m = ServeMetrics()
        n = _LABEL_LIMIT + 6
        for i in range(n):
            m.on_complete(_completion(i, tenant=f"t{i:03d}"), None)
        snap = m.report()
        tenants = set()
        for k in snap:
            if k.startswith("serve_tenant_requests_total{"):
                labels = dict(p.split("=", 1) for p in
                              k.split("{", 1)[1].rstrip("}").split(","))
                tenants.add(labels["tenant"])
        # bounded at limit+1: the first LIMIT real ids plus "other"
        assert len(tenants) == _LABEL_LIMIT + 1
        assert "other" in tenants
        assert f"t{_LABEL_LIMIT - 1:03d}" in tenants   # last one in
        assert f"t{_LABEL_LIMIT:03d}" not in tenants   # first one out
        # the fold is visible, not silent: 6 overflow tenants hit two
        # labelled families (requests + tokens) each
        overflow = default_registry().snapshot()[
            "metrics_label_overflow_total"] - before
        assert overflow == 12
        assert snap[
            "serve_tenant_requests_total{status=eos,tenant=other}"] == 6
    finally:
        reset_label_guard()


# -------------------------------------------- two-tenant chaos fleet (e2e)
MODEL_KW = {"vocab_size": 64, "max_len": 128, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 128, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}


def _tenant_trace(n=8, seed=5):
    rng = np.random.default_rng(seed)
    return [{
        "rid": i,
        "prompt": rng.integers(1, 64, int(rng.integers(3, 9))).tolist(),
        "max_new_tokens": int(rng.integers(80, 101)),
        # i%4 keeps BOTH tenants on both sides of any even/odd routing
        # split, so the victim worker's outstanding set spans tenants
        "tenant": "acme" if i % 4 in (0, 1) else "free-tier",
    } for i in range(n)]


@pytest.mark.slow
@pytest.mark.chaos
def test_two_tenant_fleet_keeps_fault_affected_from_both_tenants(tmp_path):
    """ISSUE 12 acceptance: a 2-worker fleet where tenant "acme" runs a
    1.0 head-rate override while "free-tier" rides the 1% fleet
    default; worker 0 SIGKILLed mid-decode. Every fault-affected
    request from BOTH tenants surfaces in the kept timeline under its
    original trace_id; clean free-tier traffic stays suppressed; clean
    acme traffic stays kept (the override crossed the RPC seam); the
    merged trace validates fleet-clean and completions carry their
    tenant home."""
    from ddp_practice_tpu.serve.scheduler import Request
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from tools import check_traces

    def attempt():
        trace = _tenant_trace(n=8, seed=5)
        tenant_of = {t["rid"]: t["tenant"] for t in trace}
        free = [r for r, tn in tenant_of.items() if tn == "free-tier"]
        acme = [r for r, tn in tenant_of.items() if tn == "acme"]
        # pinned: every free-tier rid is head-UNSAMPLED at 1%, so any
        # free-tier keep below is provably tail-based, not hash luck
        assert not any(head_keep(f"r{r}", 0.01) for r in free)
        tracer = TraceRecorder()
        spec = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW,
                          max_queue=64, trace=True, trace_sample=0.01,
                          trace_tenant_rates={"acme": 1.0})
        router, sup, handles = make_fleet_router(
            spec, 2, tracer=tracer,
            sup_config=SupervisorConfig(restart_base_s=0.25,
                                        restart_budget=5,
                                        ready_timeout_s=300.0),
        )
        try:
            assert tracer.sampler is not None
            assert tracer.sampler.tenant_rates == {"acme": 1.0}
            for t in trace:
                router.submit(Request(**t))

            def victim_busy():
                w = sup.worker(0)
                if w is None:
                    return False
                try:
                    st = w.client.call("ping", timeout_s=2.0)["stats"]
                    return st["active"] > 0
                except Exception:
                    return False

            deadline = time.monotonic() + 60
            while not victim_busy():
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            sup.kill(0, "SIGKILL")
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == set(tenant_of)
            # tenant rode the full seam: submit -> worker -> completion
            for rid, c in by_rid.items():
                assert c.tenant == tenant_of[rid]
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            assert {tenant_of[r] for r in migrated} \
                == {"acme", "free-tier"}, "kill touched only one tenant"
            # every fault-affected request kept, whatever its tenant;
            # clean acme kept by its override; clean free-tier
            # suppressed by the fleet default
            for rid in migrated:
                assert by_rid[rid].trace_sampled, f"r{rid} not kept"
            for rid in acme:
                assert by_rid[rid].trace_sampled, f"acme r{rid} lost"
            clean_free = [r for r in free
                          if by_rid[r].flight["failovers"] == 0
                          and by_rid[r].flight["retries"] == 0]
            for rid in clean_free:
                assert not by_rid[rid].trace_sampled
            # the kept timeline agrees with the completion bits
            chrome = tracer.to_chrome_trace()
            assert check_traces.validate(chrome) == []
            assert check_traces.validate_fleet(chrome) == []
            ids_in_trace = set()
            for e in chrome["traceEvents"]:
                a = e.get("args") or {}
                if "trace_id" in a:
                    ids_in_trace.add(a["trace_id"])
                if e.get("id") is not None:
                    ids_in_trace.add(e["id"])
            for rid in migrated + acme:
                assert f"r{rid}" in ids_in_trace
            for rid in clean_free:
                assert f"r{rid}" not in ids_in_trace
            sm = chrome["metadata"]["sampling"]
            assert sm["head_rate"] == 0.01
            assert sm["tenant_rates"] == {"acme": 1.0}
            cpath = tmp_path / "fleet.json"
            tracer.save(str(cpath))
            assert check_traces.main(["--fleet", str(cpath)]) == 0
        finally:
            sup.stop()

    for i in range(2):   # one retry for the documented XLA-CPU near-tie
        try:
            return attempt()
        except AssertionError:
            if i == 1:
                raise
