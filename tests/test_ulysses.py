"""Ulysses sequence parallelism: all-to-all head-scatter attention.

Must equal dense attention exactly (it IS dense attention after the
re-shard), causal and non-causal, under jit, and compose with the
'tensor'-axis head sharding (TP x SP).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.ops.attention import _attention, dot_product_attention
from ddp_practice_tpu.parallel.mesh import build_mesh
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.ulysses import ulysses_attention


@pytest.fixture()
def seq_mesh(devices):
    mesh = build_mesh(MeshConfig(data=1, seq=8, tensor=1))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


@pytest.fixture()
def mixed_mesh(devices):
    mesh = build_mesh(MeshConfig(data=2, seq=2, tensor=2))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


def _qkv(b=2, s=32, h=8, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.fast
def test_ulysses_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    dense = _attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, axis_name="seq", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_ulysses_inside_jit(seq_mesh):
    q, k, v = _qkv(seed=1)

    @jax.jit
    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="seq")

    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(_attention(q, k, v, causal=False)),
        rtol=2e-5,
        atol=2e-5,
    )


def test_ulysses_composes_with_tp(mixed_mesh):
    """Heads already sharded over 'tensor'; ulysses splits the rest over 'seq'."""
    q, k, v = _qkv(b=4, s=16, h=4, d=8, seed=2)
    out = dot_product_attention(q, k, v, seq_axis="seq", sp_impl="ulysses")
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_attention(q, k, v, causal=False)),
        rtol=2e-5,
        atol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(seq_mesh, causal):
    """Flash kernel as the local attention after the head scatter."""
    q, k, v = _qkv(seed=3)
    dense = _attention(q, k, v, causal=causal)
    out = ulysses_attention(
        q, k, v, axis_name="seq", causal=causal, impl="flash"
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_ulysses_flash_grad_matches_dense(seq_mesh):
    q, k, v = _qkv(seed=4)

    def loss_u(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, axis_name="seq", impl="flash") ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(_attention(q, k, v, causal=False) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v = _qkv(h=4)  # 4 heads, seq axis 8 -> indivisible
    with pytest.raises(Exception):
        jax.block_until_ready(ulysses_attention(q, k, v, axis_name="seq"))
