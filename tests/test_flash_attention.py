"""Pallas flash-attention kernel: numerics pinned to the dense reference.

Runs in interpret mode under the CPU test backend (same code path as the
compiled TPU kernel modulo Mosaic lowering). Forward and backward must
match dense attention, causal and non-causal, including bf16 inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.ops.attention import _attention, dot_product_attention
from ddp_practice_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, s=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.fast
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = _attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_flash_multiple_k_blocks():
    """seq > block size: the online-softmax accumulation crosses blocks."""
    q, k, v = _qkv(s=512, seed=1)
    want = _attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    q, k, v = _qkv(s=128, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_bf16():
    q, k, v = _qkv(s=128, seed=3, dtype=jnp.bfloat16)
    want = _attention(q, k, v, causal=False)
    got = flash_attention(q, k, v, causal=False)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_flash_causal_cross_lengths():
    """seq_q != seq_k causal uses bottom-right alignment, like _attention."""
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.normal(size=(2, 128, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 256, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 256, 2, 64)), jnp.float32)
    want = _attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_dispatch_via_impl_flag():
    q, k, v = _qkv(s=128, seed=4)
    got = dot_product_attention(q, k, v, impl="flash")
    want = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_causal_rejects_longer_queries():
    """seq_q > seq_k causal has no sound bottom-right alignment: reject."""
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
    with pytest.raises(ValueError, match="seq_q <= seq_k"):
        flash_attention(q, k, k, causal=True)


def test_block_sizes_fit_down_to_divisors():
    """Requested blocks are upper bounds: a seq that the default block
    doesn't divide fits down to the largest dividing power-of-two split
    instead of erroring (seq 1536 with default block_k 1024 -> 512)."""
    from ddp_practice_tpu.ops.flash_attention import _fit_block

    assert _fit_block(1536, 1024) == 512
    assert _fit_block(65, 512) == 65      # seq <= block: clamp to seq
    assert _fit_block(96, 64) == 32
    assert _fit_block(2048, 1024) == 1024


def test_flash_indivisible_seq_still_works():
    """seq=96 with requested block 64 (not a divisor): blocks fit down and
    numerics still match dense — the pre-fit behavior was a ValueError."""
    q, k, v = _qkv(s=96, seed=5)
    want = _attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_packed_matches_folded(causal):
    """The packed-layout kernels (round 4: attention directly on the flat
    (b, s, h*d) activations, head pairs in 128-lane column blocks) must
    agree with the folded (b*h, s, d) path — forward AND all three grads.
    On TPU the two are bit-identical; interpret mode gets a float
    tolerance."""
    from ddp_practice_tpu.ops.flash_attention import (
        _flash_lse, _heads_per_pack)

    b, s, h, d = 2, 256, 4, 64
    assert _heads_per_pack(h, d) == 2  # shapes take the packed path
    q, k, v = _qkv(b=b, s=s, h=h, d=d, seed=11)

    def folded(q, k, v):
        fold = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(
            b * h, x.shape[1], d)
        out, _ = _flash_lse(fold(q), fold(k), fold(v), causal, 512, 1024)
        return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))

    got = flash_attention(q, k, v, causal=causal)  # dispatches packed
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(folded(q, k, v)), rtol=2e-5, atol=2e-5
    )

    loss_p = lambda q, k, v: (
        flash_attention(q, k, v, causal=causal) ** 2).sum()
    loss_f = lambda q, k, v: (folded(q, k, v) ** 2).sum()
    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4
        )


def test_unpackable_heads_fall_back_to_folded():
    """h=3 with d=64 cannot pack into whole 128-lane pairs: the dispatch
    must fall back to the folded path and still match dense."""
    from ddp_practice_tpu.ops.flash_attention import _heads_per_pack

    assert _heads_per_pack(3, 64) is None
    q, k, v = _qkv(h=3, seed=13)
    want = _attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("d", [128, 256])
def test_packed_single_head_per_pack(d):
    """hpc=1 packing (d a multiple of 128: whole heads own >=128-lane
    column blocks) and the _widen lane-tile path (w > 128 for d=256) must
    match dense — the hpc=2 test never reaches either branch."""
    from ddp_practice_tpu.ops.flash_attention import _heads_per_pack

    assert _heads_per_pack(2, d) == 1
    q, k, v = _qkv(b=1, s=256, h=2, d=d, seed=17)
    want = _attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    gp = jax.grad(lambda q: (flash_attention(q, k, v, causal=True) ** 2
                             ).sum())(q)
    gd = jax.grad(lambda q: (_attention(q, k, v, causal=True) ** 2
                             ).sum())(q)
    np.testing.assert_allclose(
        np.asarray(gp), np.asarray(gd), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("causal", [False, True])
def test_fused_qkv_matches_sliced(causal):
    """flash_attention_qkv (round 4: the kernels window the raw (b, s,
    3*h*d) QKV-projection output at column offsets — q/k/v never
    materialize as slices) must match slicing q/k/v out and calling
    flash_attention: forward and the full dqkv gradient."""
    from ddp_practice_tpu.ops.flash_attention import flash_attention_qkv

    b, s, h, d = 2, 256, 4, 64
    rng = np.random.default_rng(23)
    qkv = jnp.asarray(rng.standard_normal((b, s, 3 * h * d)), jnp.float32)

    def sliced(qkv):
        hd = h * d
        rs = lambda x: x.reshape(b, s, h, d)
        return flash_attention(
            rs(qkv[..., :hd]), rs(qkv[..., hd:2 * hd]),
            rs(qkv[..., 2 * hd:]), causal=causal,
        )

    got = flash_attention_qkv(qkv, h, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got.reshape(b, s, h * d)),
        np.asarray(sliced(qkv).reshape(b, s, h * d)),
        rtol=2e-5, atol=2e-5,
    )

    # weighted-sum loss so dq/dk/dv all flow through one qkv cotangent
    w = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    g_fused = jax.grad(
        lambda t: (flash_attention_qkv(t, h, causal=causal) * w).sum()
    )(qkv)
    g_sliced = jax.grad(lambda t: (sliced(t) * w).sum())(qkv)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_sliced), rtol=2e-4, atol=2e-4
    )


def test_fused_qkv_unpackable_falls_back():
    """h*d shapes that cannot pack must still work through the fallback
    slice path inside flash_attention_qkv."""
    from ddp_practice_tpu.ops.flash_attention import (
        _heads_per_pack, flash_attention_qkv)

    b, s, h, d = 2, 128, 3, 64
    assert _heads_per_pack(h, d) is None
    rng = np.random.default_rng(29)
    qkv = jnp.asarray(rng.standard_normal((b, s, 3 * h * d)), jnp.float32)
    hd = h * d
    rs = lambda x: x.reshape(b, s, h, d)
    want = _attention(
        rs(qkv[..., :hd]), rs(qkv[..., hd:2 * hd]), rs(qkv[..., 2 * hd:]),
        causal=True,
    )
    got = flash_attention_qkv(qkv, h, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
