"""serve/supervisor.py state machine + RemoteReplicaHandle — host-pure.

No processes are spawned here: `spawn_fn` is injected with fakes and
time is a FakeClock, so the restart-backoff schedule, the
restart-budget circuit breaker, the drain path, and the handle's
salvage/heartbeat accounting replay deterministically. The real-process
truth of the same machinery lives in tests/test_worker_fleet.py
(slow + chaos).
"""

import pytest

from ddp_practice_tpu.serve.faults import (
    FaultPlan,
    FaultSpec,
    FleetFaultDriver,
    ReplicaCrashed,
)
from ddp_practice_tpu.serve.rpc import RpcRemoteError, RpcTimeout
from ddp_practice_tpu.serve.scheduler import FakeClock, Request
from ddp_practice_tpu.serve.supervisor import (
    BACKOFF,
    DRAINING,
    FAILED,
    RUNNING,
    SPAWNING,
    STOPPED,
    RemoteReplicaHandle,
    Supervisor,
    SupervisorConfig,
    fleet_targets,
)
from ddp_practice_tpu.serve.worker import WorkerSpec
from ddp_practice_tpu.utils.backoff import backoff_delay


class FakeClient:
    """Scriptable RPC client: `handler(op, fields)` -> dict or raise."""

    def __init__(self, handler=None):
        self.handler = handler or (lambda op, fields: {})
        self.calls = []
        self.closed = False

    def call(self, op, **fields):
        self.calls.append((op, fields))
        return {"ok": True, **self.handler(op, fields)}

    def close(self):
        self.closed = True


class FakeWorker:
    _next_pid = [1000]

    def __init__(self, spec, handler=None):
        FakeWorker._next_pid[0] += 1
        self.pid = FakeWorker._next_pid[0]
        self.spec = spec
        self.rc = None
        self.signals = []
        self.reaped = False
        self.telemetry_port = 9000 + self.pid % 100
        self.client = FakeClient(handler)

    def poll(self):
        return self.rc

    def kill_signal(self, sig):
        self.signals.append(sig)
        if sig in ("SIGKILL", "SIGTERM"):
            self.rc = -9

    def die(self, rc=1):
        self.rc = rc

    def reap(self, timeout_s=5.0):
        self.reaped = True
        self.client.close()


SPEC = WorkerSpec(engine={"max_slots": 2, "prompt_buckets": [8, 16]},
                  max_queue=4)
CFG = SupervisorConfig(restart_base_s=0.2, restart_factor=2.0,
                       restart_max_s=10.0, restart_jitter=0.0,
                       restart_budget=3)


def make_sup(n=1, handler=None, cfg=CFG):
    spawned = []

    def spawn(spec):
        w = FakeWorker(spec, handler)
        spawned.append(w)
        return w

    clock = FakeClock(step_s=0.01)
    sup = Supervisor([SPEC] * n, cfg, spawn_fn=spawn,
                     spawn_in_thread=False, clock=clock)
    sup.start()
    return sup, clock, spawned


# ------------------------------------------------------------- supervisor
def test_restart_backoff_schedule_is_the_shared_backoff():
    """A dying worker respawns at exactly backoff_delay(k) after each
    death — the same utils/backoff.py schedule every other retry loop
    uses, per-slot seeded."""
    sup, clock, spawned = make_sup()
    assert sup.state(0) == RUNNING and len(spawned) == 1
    for k in range(3):
        spawned[-1].die()
        t_death = clock.now()
        sup.poll()
        assert sup.state(0) == BACKOFF
        assert spawned[-1].reaped          # the corpse was collected
        want = backoff_delay(k, base_s=0.2, factor=2.0, max_s=10.0,
                             jitter=0.0, seed=CFG.seed + 0)
        # one tick before due: nothing spawns
        clock.advance(want - 0.001 - (clock.now() - t_death))
        sup.poll()
        assert sup.state(0) == BACKOFF and len(spawned) == 1 + k
        clock.advance(0.002)
        sup.poll()
        assert sup.state(0) == RUNNING and len(spawned) == 2 + k
        assert sup.restarts[0] == k + 1
        # a restarted slot is a NEW process (new pid, new client)
        assert spawned[-1].pid != spawned[-2].pid


def test_restart_budget_circuit_breaker_goes_failed():
    sup, clock, spawned = make_sup()
    for _ in range(CFG.restart_budget):
        spawned[-1].die()
        sup.poll()
        clock.advance(60.0)  # well past any backoff
        sup.poll()
        assert sup.state(0) == RUNNING
    # one death past the budget: FAILED for good, no more spawns
    spawned[-1].die()
    sup.poll()
    assert sup.state(0) == FAILED
    clock.advance(3600.0)
    sup.poll()
    assert sup.state(0) == FAILED
    assert len(spawned) == 1 + CFG.restart_budget
    assert sup.worker(0) is None


def test_rolling_window_budget_half_closes_after_storm_ages_out():
    """With restart_window_s configured, FAILED is a cool-down, not a
    grave: only restarts inside the rolling window count against the
    budget, so once the crash storm ages out the breaker half-closes
    and the slot respawns on its own — no operator in the loop."""
    cfg = SupervisorConfig(restart_base_s=0.2, restart_factor=2.0,
                           restart_max_s=10.0, restart_jitter=0.0,
                           restart_budget=3, restart_window_s=300.0)
    sup, clock, spawned = make_sup(cfg=cfg)
    for _ in range(cfg.restart_budget):       # deaths at t=0, 60, 120
        spawned[-1].die()
        sup.poll()
        clock.advance(60.0)
        sup.poll()
        assert sup.state(0) == RUNNING
    spawned[-1].die()                         # 4th death inside window
    sup.poll()
    assert sup.state(0) == FAILED and sup.worker(0) is None
    # still inside the window: the breaker stays open, nothing spawns
    clock.advance(100.0)                      # t=280; oldest was t=0
    sup.poll()
    assert sup.state(0) == FAILED
    assert len(spawned) == 1 + cfg.restart_budget
    # the t=0 restart leaves the 300 s window: half-close and rejoin
    clock.advance(30.0)                       # t=310
    sup.poll()
    assert sup.state(0) == BACKOFF
    sup.poll()                                # due immediately
    assert sup.state(0) == RUNNING
    assert len(spawned) == 2 + cfg.restart_budget


def test_revive_escape_hatch_resets_budget_and_respawns():
    """revive(slot) is the operator's override for a lifetime-budget
    FAILED slot: back in play NOW with a FRESH budget (a revive that
    instantly re-tripped would be no escape), lifetime restart
    telemetry preserved. A no-op on any non-FAILED slot."""
    sup, clock, spawned = make_sup()
    sup.revive(0)                             # no-op on a live slot
    assert sup.state(0) == RUNNING
    for _ in range(CFG.restart_budget + 1):
        spawned[-1].die()
        sup.poll()
        clock.advance(60.0)
        sup.poll()
    assert sup.state(0) == FAILED
    lifetime = sup.restarts[0]
    assert lifetime == CFG.restart_budget
    clock.advance(3600.0)                     # no window: FAILED stays
    sup.poll()
    assert sup.state(0) == FAILED
    sup.revive(0)
    assert sup.state(0) == BACKOFF
    sup.poll()                                # due immediately
    assert sup.state(0) == RUNNING
    assert len(spawned) == 2 + CFG.restart_budget
    assert sup.restarts[0] == lifetime        # telemetry preserved
    # the budget really is fresh: the next death restarts, no re-trip
    spawned[-1].die()
    sup.poll()
    assert sup.state(0) == BACKOFF
    clock.advance(60.0)
    sup.poll()
    assert sup.state(0) == RUNNING


def test_spawn_failure_consumes_budget_and_reschedules():
    """A spec that cannot boot must walk the same backoff->budget->
    FAILED path as a crash loop, not spin forever."""
    boots = []

    def flaky_spawn(spec):
        boots.append(1)
        raise RuntimeError("no ready line")

    clock = FakeClock()
    sup = Supervisor([SPEC], CFG, spawn_fn=flaky_spawn,
                     spawn_in_thread=False, clock=clock)
    # start() itself failing is the caller's problem; enter the loop
    # with a worker that dies immediately instead
    ok = FakeWorker(SPEC)
    sup.workers[0] = ok
    sup.states[0] = RUNNING
    ok.die()
    while sup.state(0) not in (FAILED,):
        sup.poll()
        clock.advance(60.0)
    assert sup.state(0) == FAILED
    assert len(boots) == CFG.restart_budget


def test_stop_drains_gracefully_and_reaps():
    shutdowns = []

    def handler(op, fields):
        if op == "shutdown":
            shutdowns.append(1)
        return {}

    sup, clock, spawned = make_sup(n=2, handler=handler)

    # graceful workers exit when told to (rpc shutdown -> rc 0)
    def exiting_handler(op, fields):
        out = handler(op, fields)
        if op == "shutdown":
            for w in spawned:
                w.rc = 0
        return out

    for w in spawned:
        w.client.handler = exiting_handler
    sup.stop()
    assert all(w.reaped for w in spawned)
    assert all(sup.state(i) == STOPPED for i in range(2))
    assert len(shutdowns) == 2            # one graceful ask per worker
    assert all(not w.signals for w in spawned)   # never escalated
    assert all(w.client.closed for w in spawned)


# ------------------------------------------------------------- the handle
def make_handle(handler=None, heartbeat_timeout_s=2.0):
    sup, clock, spawned = make_sup(handler=handler)
    h = RemoteReplicaHandle(0, sup, SPEC, clock=clock,
                            heartbeat_timeout_s=heartbeat_timeout_s)
    return h, sup, clock, spawned


def _poll_reply(completions=(), inflight=(), queue=0, active=0):
    return {
        "completions": list(completions), "inflight": list(inflight),
        "watermark": len(completions),
        "stats": {"queue": queue, "active": active, "max_slots": 2,
                  "compile_stats": {"prefill": 1}},
    }


def test_handle_salvage_point_feeds_evacuate():
    """poll refreshes tokens-so-far; a later death evacuates exactly the
    last salvage — the cross-process mirror of Scheduler.evacuate."""
    state = {"inflight": []}

    def handler(op, fields):
        if op == "poll":
            return _poll_reply(inflight=state["inflight"])
        return {"accepted": True}

    h, sup, clock, spawned = make_handle(handler)
    req = Request(rid=7, prompt=[1, 2, 3], max_new_tokens=8,
                  arrival=0.0, trace_id="r7")
    h.submit(req)
    assert 7 in h.outstanding
    state["inflight"] = [{"rid": 7, "tokens": [5, 6], "ftt": 0.5,
                          "phases": {"queue_s": 0.1, "prefill_s": 0.2,
                                     "decode_s": 0.3}}]
    h.step()
    assert h.outstanding[7]["tokens"] == [5, 6]
    # the worker dies for real: step raises, evacuate hands back the
    # ORIGINAL request with the salvaged tokens
    spawned[-1].die()
    with pytest.raises(ReplicaCrashed):
        h.step()
    ev = h.evacuate()
    assert len(ev) == 1
    evreq, tokens, ftt, phases = ev[0]
    assert evreq is req and tokens == [5, 6] and ftt == 0.5
    assert phases["decode_s"] == 0.3
    assert h.outstanding == {}


def test_handle_completion_consumption_clears_outstanding():
    comp = {"rid": 3, "tokens": [9, 9], "status": "length",
            "arrival": 0.0, "finish": 1.0, "ttft": 0.1, "tpot": 0.05,
            "flight": None}
    replies = {"n": 0}

    def handler(op, fields):
        if op == "poll":
            replies["n"] += 1
            return _poll_reply(completions=[comp] if replies["n"] == 1
                               else [])
        return {"accepted": True}

    h, sup, clock, spawned = make_handle(handler)
    h.submit(Request(rid=3, prompt=[1], max_new_tokens=2, arrival=0.0))
    h.step()
    got = h.poll()
    assert [c.rid for c in got] == [3] and got[0].status == "length"
    assert h.outstanding == {}
    assert h.poll() == []  # consume-once


def test_handle_stale_heartbeat_sigkills_and_raises():
    """A worker alive by waitpid but silent on the wire (SIGSTOP) must
    be put down with a REAL SIGKILL once the heartbeat budget runs out
    — silence is death, but only after the budget, so one slow tick
    isn't a failover."""

    def handler(op, fields):
        if op == "poll":
            raise RpcTimeout("stalled")
        return {}

    h, sup, clock, spawned = make_handle(handler, heartbeat_timeout_s=1.0)
    h.step()      # first silent tick: starts the staleness clock
    assert spawned[-1].signals == []
    clock.advance(0.5)
    h.step()      # still inside the budget: no kill, no crash
    assert spawned[-1].signals == []
    clock.advance(0.6)
    with pytest.raises(ReplicaCrashed, match="stale"):
        h.step()
    assert spawned[-1].signals == ["SIGKILL"]


def test_handle_submit_failure_breaks_on_next_step_and_keeps_request():
    def handler(op, fields):
        if op == "submit":
            raise RpcTimeout("wire down")
        return _poll_reply()

    h, sup, clock, spawned = make_handle(handler)
    req = Request(rid=1, prompt=[1], max_new_tokens=2, arrival=0.0)
    h.submit(req)
    with pytest.raises(ReplicaCrashed):
        h.step()
    assert [t[0] for t in h.evacuate()] == [req]


def test_handle_probe_and_restart_resync():
    """probe_ok needs a RUNNING process that answers ping; restart()
    resets the watermark to the new process's empty completions."""
    h, sup, clock, spawned = make_handle(
        lambda op, fields: _poll_reply() if op == "poll" else {}
    )
    h.step()
    h.consumed = 17
    spawned[-1].die()
    with pytest.raises(ReplicaCrashed):
        h.step()
    assert not h.probe_ok(clock.now())     # corpse: no process
    # supervisor brings a replacement up after the backoff
    clock.advance(60.0)
    sup.poll()
    assert sup.state(0) == RUNNING
    assert h.probe_ok(clock.now())
    h.restart()
    assert h.consumed == 0 and h.heartbeat_age() == 0.0


def test_fleet_fault_driver_fires_each_kill_once_in_order():
    """`kill` specs fire at their at_s edge, exactly once, through the
    injected kill_fn — and never leak into the per-scheduler injector
    (they target processes, not schedulers)."""
    plan = FaultPlan([
        FaultSpec(kind="kill", at_s=2.0, replica=1, sig="SIGSTOP"),
        FaultSpec(kind="kill", at_s=1.0, replica=0),
    ])
    fired = []
    drv = FleetFaultDriver(plan, lambda r, s: fired.append((r, s)))
    drv.poll(0.5)
    assert fired == [] and not drv.done
    drv.poll(1.0)
    assert fired == [(0, "SIGKILL")]
    drv.poll(5.0)   # a LATE poll still fires everything due
    assert fired == [(0, "SIGKILL"), (1, "SIGSTOP")] and drv.done
    drv.poll(9.0)
    assert len(fired) == 2          # once means once
    # kill specs never reach a scheduler's fault hook
    assert plan.injector(0) is None and plan.injector(1) is None
    # and they survive the JSON round trip like every other fault kind
    plan2 = FaultPlan.from_json(plan.to_json())
    assert [(f.replica, f.sig) for f in plan2.kills()] \
        == [(0, "SIGKILL"), (1, "SIGSTOP")]
    with pytest.raises(ValueError, match="signal"):
        FaultSpec(kind="kill", sig="SIGWINCH")


def test_fleet_targets_shape():
    h, sup, clock, spawned = make_handle(
        lambda op, fields: _poll_reply() if op == "poll" else {}
    )
    h.step()
    t = fleet_targets(sup, [h])
    assert t[0]["up"] and t[0]["pid"] == spawned[-1].pid
    assert t[0]["port"] == spawned[-1].telemetry_port
    assert t[0]["heartbeat_age_s"] == 0.0
    spawned[-1].die()
    sup.poll()
    t = fleet_targets(sup, [h])
    assert not t[0]["up"] and t[0]["pid"] is None
    assert t[0]["state"] in (BACKOFF, SPAWNING)


# ------------------------------------------------- elastic actuators
class DrainingWorker(FakeWorker):
    """A FakeWorker that honors SIGTERM as a REQUEST, not a death:
    only SIGKILL fells it, so the DRAINING window is observable (the
    harness FakeWorker drops dead on SIGTERM, which pins the fast path
    but hides the deadline machinery)."""

    def kill_signal(self, sig):
        self.signals.append(sig)
        if sig == "SIGKILL":
            self.rc = -9


def make_sup_draining(n=1, handler=None, cfg=None):
    spawned = []

    def spawn(spec):
        w = DrainingWorker(spec, handler)
        spawned.append(w)
        return w

    clock = FakeClock(step_s=0.01)
    sup = Supervisor([SPEC] * n, cfg or CFG, spawn_fn=spawn,
                     spawn_in_thread=False, clock=clock)
    sup.start()
    return sup, clock, spawned


def test_shrink_running_drains_rpc_then_sigterm_no_budget():
    """shrink() of a RUNNING slot: drain rpc first (refusals start even
    if signal delivery lags), then SIGTERM -> DRAINING; the exit is
    retired to STOPPED with zero budget charge and zero respawn."""
    sup, clock, spawned = make_sup(n=2)
    assert sup.active_slots() == 2
    assert sup.shrink(1) == DRAINING
    w = spawned[1]
    assert ("drain", {"timeout_s": 1.0, "retries": 0}) in w.client.calls
    assert w.signals == ["SIGTERM"]
    # a DRAINING worker is still a live process to the handle's eyes
    assert sup.worker(1) is w and not sup.alive(1)
    assert sup.draining(1) and sup.active_slots() == 1
    # the harness FakeWorker exits on SIGTERM: next poll retires it
    sup.poll()
    assert sup.state(1) == STOPPED and w.reaped
    assert sup.worker(1) is None
    # an intentional goodbye is not a crash: no budget, no respawn
    assert sup.restarts[1] == 0 and sup._budget_used[1] == 0
    clock.advance(3600.0)
    sup.poll()
    assert sup.state(1) == STOPPED and len(spawned) == 2
    # slot 0 untouched throughout
    assert sup.state(0) == RUNNING


def test_shrink_draining_deadline_escalates_to_sigkill():
    """A drain that never converges is put down at shrink_kill_after_s
    — and the SIGKILLed corpse still retires to STOPPED, not BACKOFF."""
    cfg = SupervisorConfig(restart_base_s=0.2, restart_jitter=0.0,
                           restart_budget=3, shrink_kill_after_s=5.0)
    sup, clock, spawned = make_sup_draining(cfg=cfg)
    sup.shrink(0)
    w = spawned[0]
    assert sup.state(0) == DRAINING and w.signals == ["SIGTERM"]
    clock.advance(4.9)
    sup.poll()                      # inside the grace window: no kill
    assert sup.state(0) == DRAINING and w.signals == ["SIGTERM"]
    clock.advance(0.2)
    sup.poll()                      # past the deadline: SIGKILL
    assert w.signals == ["SIGTERM", "SIGKILL"]
    sup.poll()                      # corpse collected
    assert sup.state(0) == STOPPED and w.reaped
    assert sup.restarts[0] == 0 and sup._budget_used[0] == 0


def test_shrink_chaos_sigkill_mid_drain_is_not_a_crash():
    """Chaos SIGKILLs the worker WHILE it drains: the slot must retire
    to STOPPED — a draining slot that respawned would undo the
    scale-down, and a budget charge would punish an intentional act."""
    sup, clock, spawned = make_sup_draining()
    sup.shrink(0)
    assert sup.state(0) == DRAINING
    spawned[0].die(rc=-9)           # external SIGKILL, not ours
    sup.poll()
    assert sup.state(0) == STOPPED and spawned[0].reaped
    assert sup.restarts[0] == 0 and sup._budget_used[0] == 0
    clock.advance(3600.0)
    sup.poll()
    assert sup.state(0) == STOPPED and len(spawned) == 1


def test_shrink_backoff_cancels_pending_respawn_without_budget():
    """Satellite pin: shrink() of a slot sitting in BACKOFF cancels the
    scheduled respawn outright — the slot goes STOPPED, the backoff
    timer never fires, and the budget ledger is exactly what the crash
    alone made it."""
    sup, clock, spawned = make_sup()
    spawned[-1].die()
    sup.poll()
    assert sup.state(0) == BACKOFF
    used_before = sup._budget_used[0]
    restarts_before = sup.restarts[0]
    assert sup.shrink(0) == STOPPED
    clock.advance(3600.0)           # way past every backoff delay
    sup.poll()
    assert sup.state(0) == STOPPED and len(spawned) == 1
    assert sup._budget_used[0] == used_before
    assert sup.restarts[0] == restarts_before


def test_shrink_spawning_cancels_inflight_attempt():
    """Satellite pin: shrink() of a slot whose RESPAWN is in flight on
    a spawn thread flags the attempt; _collect_spawn reaps the fresh
    worker instead of seating it, and the cancellation itself charges
    no budget beyond what the original crash already did."""
    import threading
    import time as _time

    release = threading.Event()
    spawned = []

    def spawn(spec):
        if spawned:                     # first boot is synchronous
            release.wait(5.0)
        w = FakeWorker(spec)
        spawned.append(w)
        return w

    clock = FakeClock(step_s=0.01)
    sup = Supervisor([SPEC], CFG, spawn_fn=spawn,
                     spawn_in_thread=True, clock=clock)
    sup.start()
    assert sup.state(0) == RUNNING
    spawned[0].die()
    sup.poll()                          # death -> BACKOFF (1 budget)
    clock.advance(60.0)
    sup.poll()                          # due -> SPAWNING, blocked
    assert sup.state(0) == SPAWNING
    used = sup._budget_used[0]
    rest = sup.restarts[0]
    assert sup.shrink(0) == SPAWNING    # stays until the attempt lands
    assert sup.active_slots() == 1      # still in the pipeline... just
    release.set()
    deadline = _time.monotonic() + 5.0
    while sup.state(0) == SPAWNING and _time.monotonic() < deadline:
        sup.poll()
        _time.sleep(0.005)
    assert sup.state(0) == STOPPED
    assert len(spawned) == 2
    assert spawned[1].reaped            # born cancelled, reaped
    assert sup.worker(0) is None
    assert sup._budget_used[0] == used and sup.restarts[0] == rest
    assert sup.active_slots() == 0


def test_grow_appends_warm_and_cold_slots():
    """grow() is append-only: a warm standby seats RUNNING immediately
    (promotion is a list append, not a spawn); a cold grow rides the
    normal BACKOFF->spawn pipeline due NOW, with zero budget charge."""
    sup, clock, spawned = make_sup()
    warm = FakeWorker(SPEC)
    slot = sup.grow(SPEC, worker=warm)
    assert slot == 1
    assert sup.state(1) == RUNNING and sup.worker(1) is warm
    assert sup.active_slots() == 2 and len(spawned) == 1  # no spawn
    cold = sup.grow(SPEC)
    assert cold == 2 and sup.state(2) == BACKOFF
    sup.poll()                          # due immediately
    assert sup.state(2) == RUNNING and len(spawned) == 2
    assert sup.restarts[2] == 0 and sup._budget_used[2] == 0
    # slot ids are stable: shrink leaves a tombstone, never a hole
    sup.shrink(1)
    sup.poll()
    assert sup.state(1) in (DRAINING, STOPPED)
    assert sup.grow(SPEC, worker=FakeWorker(SPEC)) == 3


def test_shrink_out_of_range_raises():
    sup, clock, spawned = make_sup()
    with pytest.raises(ValueError, match="shrink targets slot 5"):
        sup.shrink(5)


def test_fleet_targets_reports_draining_and_kv():
    """Federated labels survive a scale-down: a DRAINING slot is still
    a target (its last heartbeats matter) but flagged so the verdict
    and tools/check_fleet.py can skip it; kv summaries ride along."""
    h, sup, clock, spawned = make_handle(
        lambda op, fields: _poll_reply() if op == "poll" else {}
    )
    h.step()
    t = fleet_targets(sup, [h])
    assert t[0]["draining"] is False
    assert "kv" in t[0]
    sup.shrink(0)
    t = fleet_targets(sup, [h])
    assert t[0]["draining"] is True
