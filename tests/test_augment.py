"""On-device augmentation (ops/augment.py): random crop + horizontal flip.

The reference has no augmentation (bare ToTensor, origin_main.py:89);
these pin the framework's own contract: deterministic per (seed, step),
shape-preserving, actually stochastic across steps, and OFF by default
(the unaugmented step is bit-identical to a step built without the flag).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.ops.augment import augment_rng, random_crop_flip


def _images(b=8, h=16, w=16, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.float32)


def test_deterministic_per_key(devices):
    x = _images()
    k = augment_rng(0, 7)
    a = np.asarray(random_crop_flip(x, k))
    b = np.asarray(random_crop_flip(x, k))
    np.testing.assert_array_equal(a, b)
    # different step -> different augmentation
    c = np.asarray(random_crop_flip(x, augment_rng(0, 8)))
    assert not np.array_equal(a, c)


@pytest.mark.fast
def test_shapes_preserved(devices):
    x = _images(b=4, h=28, w=28, c=1)
    y = random_crop_flip(x, jax.random.PRNGKey(0), pad=4)
    assert y.shape == x.shape
    assert y.dtype == x.dtype


def test_flip_only_is_mirror_or_identity(devices):
    x = _images(b=16)
    y = np.asarray(random_crop_flip(x, jax.random.PRNGKey(3), pad=0))
    xs = np.asarray(x)
    mirrored = xs[:, :, ::-1, :]
    flips = 0
    for i in range(16):
        same = np.array_equal(y[i], xs[i])
        mirr = np.array_equal(y[i], mirrored[i])
        assert same or mirr
        flips += int(mirr and not same)
    assert 0 < flips < 16  # both outcomes occur at p=1/2 over 16 draws


def test_crop_is_translation(devices):
    """pad=2, flip off: every output is the input shifted by <= 2 px with
    zero fill — check via cross-correlation against all 25 offsets."""
    x = _images(b=4, h=12, w=12, c=1, seed=5)
    y = np.asarray(random_crop_flip(x, jax.random.PRNGKey(9), pad=2,
                                    flip=False))
    xs = np.asarray(x)
    pad = np.pad(xs, ((0, 0), (2, 2), (2, 2), (0, 0)))
    for i in range(4):
        assert any(
            np.array_equal(y[i], pad[i, dy:dy + 12, dx:dx + 12])
            for dy in range(5) for dx in range(5)
        )


def test_augmented_step_trains_and_default_is_off(devices):
    """--augment changes the training inputs (loss differs from the
    unaugmented step on the same batch) and the default path is
    bit-identical to a factory call that never heard of the flag."""
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import make_train_step

    model = create_model("convnet")
    tx = make_optimizer(TrainConfig())
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(
            rng.integers(0, 256, (8, 28, 28, 1)), jnp.uint8
        ),
        "label": jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32),
    }

    def fresh_state():
        return create_state(
            model, tx, rng=jax.random.PRNGKey(0),
            sample_input=jnp.zeros((1, 28, 28, 1)),
        )

    _, m_plain = make_train_step(model, tx)(fresh_state(), batch)
    _, m_off = make_train_step(model, tx, augment=False)(
        fresh_state(), batch
    )
    _, m_aug = make_train_step(model, tx, augment=True)(
        fresh_state(), batch
    )
    assert float(m_plain["loss"]) == float(m_off["loss"])  # bit-identical
    assert float(m_aug["loss"]) != float(m_plain["loss"])
    assert np.isfinite(float(m_aug["loss"]))


class TestRandomResizedCrop:
    """Round 4: the ImageNet-rung augmentation (RRC)."""

    def _img(self, b=4, h=32, w=32, c=3, seed=0):
        import numpy as np
        return jnp.asarray(
            np.random.default_rng(seed).random((b, h, w, c)), jnp.float32
        )

    def test_deterministic_per_key(self):
        from ddp_practice_tpu.ops.augment import random_resized_crop

        x = self._img()
        k = jax.random.PRNGKey(7)
        a = random_resized_crop(x, k)
        b = random_resized_crop(x, k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = random_resized_crop(x, jax.random.PRNGKey(8))
        assert float(jnp.max(jnp.abs(a - c))) > 1e-3

    def test_identity_at_full_scale_unit_ratio(self):
        """scale=(1,1), ratio=(1,1), no flip: the crop is the whole image
        and the resample is the identity map."""
        from ddp_practice_tpu.ops.augment import random_resized_crop

        x = self._img(seed=1)
        y = random_resized_crop(
            x, jax.random.PRNGKey(0), scale=(1.0, 1.0),
            ratio=(1.0, 1.0), flip=False,
        )
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x), rtol=1e-5, atol=1e-5
        )

    def test_static_shapes_and_values_bounded(self):
        from ddp_practice_tpu.ops.augment import random_resized_crop

        x = self._img(b=8, seed=2)
        y = jax.jit(random_resized_crop)(x, jax.random.PRNGKey(3))
        assert y.shape == x.shape
        # linear interpolation of values in [0,1] stays in [0,1]
        assert float(y.min()) >= -1e-5 and float(y.max()) <= 1.0 + 1e-5

    def test_apply_augment_dispatch(self):
        from ddp_practice_tpu.ops.augment import (
            apply_augment, random_crop_flip, random_resized_crop)

        x = self._img(seed=3)
        k = jax.random.PRNGKey(4)
        np.testing.assert_array_equal(
            np.asarray(apply_augment(x, k, False)), np.asarray(x))
        np.testing.assert_array_equal(
            np.asarray(apply_augment(x, k, True)),
            np.asarray(random_crop_flip(x, k)))
        np.testing.assert_array_equal(
            np.asarray(apply_augment(x, k, "crop_flip")),
            np.asarray(random_crop_flip(x, k)))
        np.testing.assert_array_equal(
            np.asarray(apply_augment(x, k, "rrc")),
            np.asarray(random_resized_crop(x, k)))
        with pytest.raises(ValueError, match="augment kind"):
            apply_augment(x, k, "cutmix")
