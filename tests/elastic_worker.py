"""Subprocess body for test_elastic.py::test_fit_recovers_from_injected_failure.

The e2e elastic-recovery fit segfaults FLAKILY on this image's XLA CPU
(crash inside block_until_ready, load/memory dependent — reproduces on
the untouched seed tree; see CHANGES.md PR 2). A segfault in-process
kills the whole pytest session, so the test runs this script in a child
process: an ordinary assertion failure comes back as a normal exit code,
while the known SIGSEGV flake is detected by the parent (negative
returncode) and skipped instead of nuking the run.

Prints ALL_OK as the last line on success (the parent asserts on it,
the tests/mp_worker.py convention).
"""

from __future__ import annotations

import sys


def main(workdir: str) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ddp_practice_tpu.config import MeshConfig, TrainConfig
    from ddp_practice_tpu.train import loop as loop_mod

    cfg = TrainConfig(
        dataset="synthetic",
        epochs=2,
        batch_size=8,
        optimizer="adam",
        learning_rate=1e-3,
        log_every_steps=0,
        max_steps_per_epoch=4,
        checkpoint_dir=workdir + "/ck",
        checkpoint_every_epochs=1,
        max_restarts=1,
        mesh=MeshConfig(data=-1),
    )

    original_fit = loop_mod.Trainer._fit_inner
    state = {"attempts": 0}

    def sabotaged(self):
        state["attempts"] += 1
        if state["attempts"] == 1:
            # let epoch 1 finish (checkpoint written), then die
            self.train_epoch(0)
            self.save()
            raise RuntimeError("injected mid-training failure")
        return original_fit(self)

    loop_mod.Trainer._fit_inner = sabotaged
    try:
        summary = loop_mod.fit(cfg)
    finally:
        loop_mod.Trainer._fit_inner = original_fit
    assert state["attempts"] == 2, state
    assert np.isfinite(summary["accuracy"]), summary
    # resumed run restored the epoch-1 checkpoint (step 4) and trained
    # ONLY epoch 2 — completed epochs are not replayed: exactly 2*4 steps
    assert summary["steps"] == 8, summary
    print("ALL_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
