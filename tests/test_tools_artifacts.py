"""The offline tools exercised over CHECKED-IN bench artifacts, via
their real CLIs (subprocess, exit codes asserted) — so tools/
check_traces.py and tools/check_slo.py cannot silently rot while the
modules they validate move on (ISSUE 5 CI satellite).

The artifacts are a deterministic FakeClock 2-replica chaos run
(nan_logits fault plan, SLO watchdog armed):

- tests/data/bench_trace.json      — the exit-time Chrome dump
- tests/data/bench_telemetry.jsonl — the STREAMED telemetry of the same
  run (trace events, flight records, alert edges, metrics snapshots)

Both forms must stay validator-clean; the JSONL must render an SLO
verdict both ways (the chaos run violates a tight error-rate SLO and
meets a loose one).
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE = os.path.join(ROOT, "tests", "data", "bench_trace.json")
TELEMETRY = os.path.join(ROOT, "tests", "data", "bench_telemetry.jsonl")
# federated-fleet /healthz snapshots (ScrapeFederator output shape):
# _ok is a 2-worker healthy fleet (full-plane wrapper form, metrics
# included); _bad has one FAILED slot (restart budget spent) and one
# heartbeat-stale worker — the two verdicts check_fleet exists to catch
FLEET_OK = os.path.join(ROOT, "tests", "data", "fleet_healthz_ok.json")
FLEET_BAD = os.path.join(ROOT, "tests", "data", "fleet_healthz_bad.json")
# the elastic pair (ISSUE 14): a fleet mid-scale-down whose draining
# worker has gone quiet ON PURPOSE, and the same snapshot with the
# drain flag unset + an autoscaler size outside [min, max]
ELASTIC_OK = os.path.join(ROOT, "tests", "data",
                          "fleet_healthz_autoscale_ok.json")
ELASTIC_BAD = os.path.join(ROOT, "tests", "data",
                           "fleet_healthz_autoscale_bad.json")
# the cache-aware pair (ISSUE 15): _ok is a 2-worker fleet whose
# heartbeats carry the full kv summary + prefix digest (one full
# frame, one delta frame — both wire forms rendered); _bad has a
# worker claiming more blocks in use than its pool holds — the
# accounting the affinity router scores against is lying
CACHE_OK = os.path.join(ROOT, "tests", "data",
                        "fleet_healthz_cache_ok.json")
CACHE_BAD = os.path.join(ROOT, "tests", "data",
                         "fleet_healthz_cache_bad.json")
# streaming exactly-once audit artifacts: a deterministic FakeClock
# 2-replica run with a scripted mid-stream crash (so the PASSING
# artifact contains resumed markers — failover is part of the
# contract, not a violation); _bad is the same run with one chunk line
# replayed (duplicate seq + token overlap) and one stream's terminal
# dropped (ended in silence)
STREAM_OK = os.path.join(ROOT, "tests", "data", "stream_chunks_ok.jsonl")
STREAM_BAD = os.path.join(ROOT, "tests", "data", "stream_chunks_bad.jsonl")

# the SLO the artifact run was recorded against (it violates this one)
TIGHT_SLO = json.dumps({
    "error_rate": 0.05, "fast_window_s": 0.3, "slow_window_s": 1.0,
    "trip_burn": 2.0, "resolve_burn": 1.0, "min_events": 3,
})
LOOSE_SLO = json.dumps({"error_rate": 0.5})


def _run(*argv):
    return subprocess.run(
        [sys.executable, *argv], capture_output=True, text=True,
        cwd=ROOT, timeout=120,
    )


def test_check_traces_cli_accepts_both_artifact_forms():
    r = _run("tools/check_traces.py", TRACE, TELEMETRY)
    assert r.returncode == 0, r.stdout + r.stderr
    # one OK verdict per file, and the stream form found real spans
    assert r.stdout.count(": OK") == 2
    assert "decode_burst" in r.stdout


def test_check_traces_cli_exit_code_on_corruption(tmp_path):
    # mid-file corruption is an error (only the TAIL may be truncated)
    lines = open(TELEMETRY).read().strip().split("\n")
    lines[2] = lines[2][: len(lines[2]) // 2]
    bad = tmp_path / "corrupt.jsonl"
    bad.write_text("\n".join(lines) + "\n")
    r = _run("tools/check_traces.py", str(bad))
    assert r.returncode == 1
    assert "INVALID" in r.stdout
    # a truncated FINAL line alone is tolerated (the SIGKILL signature)
    tail_cut = tmp_path / "tail.jsonl"
    tail_cut.write_text("\n".join(lines[:2]) + "\n" + lines[3][:20])
    r = _run("tools/check_traces.py", str(tail_cut))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "crash-truncated" in r.stdout
    # a Chrome dump truncated mid-save is ONE broken line: it must not
    # slip through as an "empty but OK" stream — and nor may an empty
    # file
    cut_dump = tmp_path / "cut_dump.json"
    cut_dump.write_text(open(TRACE).read()[:200])
    assert _run("tools/check_traces.py", str(cut_dump)).returncode == 1
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert _run("tools/check_traces.py", str(empty)).returncode == 1


def test_check_slo_cli_renders_violation_and_pass():
    r = _run("tools/check_slo.py", "--slo", TIGHT_SLO, TELEMETRY)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "SLO VIOLATED" in r.stdout
    assert "error_rate" in r.stdout and "VIOLATED" in r.stdout
    assert "trip" in r.stdout  # the recorded alert timeline is shown
    r = _run("tools/check_slo.py", "--slo", LOOSE_SLO, TELEMETRY)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_check_slo_cli_json_mode_and_bad_inputs(tmp_path):
    r = _run("tools/check_slo.py", "--slo", TIGHT_SLO, "--json", TELEMETRY)
    assert r.returncode == 1
    report = json.loads(r.stdout)[TELEMETRY]
    assert report["ok"] is False and report["trips"] == 1
    assert report["objectives"]["error_rate"]["measured"] > 0.05
    # unreadable input and a bad --slo are distinguishable from a
    # violation (exit 2, not 1)
    assert _run("tools/check_slo.py", "--slo", TIGHT_SLO,
                str(tmp_path / "missing.jsonl")).returncode == 2
    assert _run("tools/check_slo.py", "--slo", "{not json",
                TELEMETRY).returncode == 2


def test_check_fleet_cli_exit_codes_over_artifacts(tmp_path):
    """ISSUE-7 CI satellite: both verdicts pinned through the real CLI.
    exit 0 = healthy fleet, 1 = dead/stale/FAILED worker, 2 =
    unreadable probe input — an operator's cron can tell a broken
    fleet from a broken probe."""
    r = _run("tools/check_fleet.py", FLEET_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert ": OK" in r.stdout
    r = _run("tools/check_fleet.py", FLEET_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FLEET UNHEALTHY" in r.stdout
    assert "restart budget exhausted" in r.stdout
    assert "heartbeat stale" in r.stdout
    # a generous heartbeat budget forgives staleness but NOT the
    # failed slot — the exit code stays 1
    r = _run("tools/check_fleet.py", "--max-heartbeat-age", "100",
             FLEET_BAD)
    assert r.returncode == 1 and "restart budget" in r.stdout
    # --json is machine-readable and keeps the code
    r = _run("tools/check_fleet.py", "--json", FLEET_BAD)
    assert r.returncode == 1
    rep = json.loads(r.stdout)[FLEET_BAD]
    assert rep["ok"] is False and rep["workers"]["0"] == "dead"
    # unreadable inputs are exit 2, not a fake verdict
    assert _run("tools/check_fleet.py",
                str(tmp_path / "missing.json")).returncode == 2
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    assert _run("tools/check_fleet.py", str(garbage)).returncode == 2
    notfleet = tmp_path / "notfleet.json"
    notfleet.write_text('{"status": "HEALTHY"}')
    assert _run("tools/check_fleet.py", str(notfleet)).returncode == 2


def test_check_fleet_verdict_as_library_too():
    from tools.check_fleet import fleet_verdict, load_snapshot

    ok, problems = fleet_verdict(load_snapshot(FLEET_OK))
    assert ok and problems == []
    ok, problems = fleet_verdict(load_snapshot(FLEET_BAD))
    assert not ok and len(problems) >= 3  # dead + failed + stale
    # the OK artifact also carries the federated /metrics text: the
    # worker relabel is pinned so the rollup format can't drift
    snap = json.load(open(FLEET_OK))
    assert 'fleet_worker_up{worker="0"} 1' in snap["metrics"]
    assert 'serve_tokens_total{worker="1"}' in snap["metrics"]


def test_check_fleet_autoscale_exit_codes_both_ways(tmp_path):
    """ISSUE-14 satellite: the elastic verdict pinned both ways over
    checked-in artifacts. A draining worker's dead probe and stale
    heartbeat are the drain WORKING (exit 0, worker skipped); the same
    silence without the drain flag pages, and an autoscaler size
    outside [min, max] — the control loop and the supervisor
    disagreeing about the world — is a problem in its own right."""
    r = _run("tools/check_fleet.py", ELASTIC_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert ": OK" in r.stdout
    assert "[draining]" in r.stdout          # listed, annotated, skipped
    assert "autoscaler: size 2 (min 1, max 3)" in r.stdout
    assert "last event: down (slo_resolved)" in r.stdout
    r = _run("tools/check_fleet.py", ELASTIC_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FLEET UNHEALTHY" in r.stdout
    assert "worker 2: status dead" in r.stdout
    assert "fleet size 4 above max 3" in r.stdout
    # --json carries the autoscaler block for machine consumers
    r = _run("tools/check_fleet.py", "--json", ELASTIC_OK)
    assert r.returncode == 0
    rep = json.loads(r.stdout)[ELASTIC_OK]
    assert rep["ok"] is True
    assert rep["autoscaler"]["size"] == 2
    assert rep["autoscaler"]["draining"] == [2]


def test_check_fleet_autoscale_verdict_as_library():
    from tools.check_fleet import fleet_verdict, load_snapshot

    ok, problems = fleet_verdict(load_snapshot(ELASTIC_OK))
    assert ok and problems == []
    ok, problems = fleet_verdict(load_snapshot(ELASTIC_BAD))
    assert not ok
    assert any("above max" in p for p in problems)
    assert any("worker 2" in p for p in problems)


def test_check_fleet_cache_exit_codes_both_ways():
    """ISSUE-15 satellite: the heartbeat-carried cache summary rendered
    per worker (blocks used/shared, hit rate, digest version/age — the
    very payload serve/affinity.py scores against) and judged: a worker
    claiming more blocks in use than its pool holds is a page, because
    an affinity router trusting that summary routes into a lie."""
    r = _run("tools/check_fleet.py", CACHE_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert ": OK" in r.stdout
    # both workers render a cache line; worker 0 published a full
    # digest frame, worker 1 a delta frame — n counts entries either way
    assert "cache: blocks 31/47 (9 shared)" in r.stdout
    assert "hit rate 80.0%" in r.stdout
    assert "digest v7 (4 prefixes, age 0.18s)" in r.stdout
    assert "cache: blocks 18/47 (4 shared)" in r.stdout
    assert "digest v3 (2 prefixes, age 0.27s)" in r.stdout
    r = _run("tools/check_fleet.py", CACHE_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FLEET UNHEALTHY" in r.stdout
    assert ("worker 0: cache accounting broken (61 blocks used of 47)"
            in r.stdout)
    # a kv summary WITHOUT a digest is fine (pre-ISSUE-15 worker, or
    # digests disabled): rendered without the digest suffix, no page
    assert "worker 1: cache accounting" not in r.stdout


def test_check_fleet_cache_verdict_as_library():
    from tools.check_fleet import fleet_verdict, load_snapshot

    ok, problems = fleet_verdict(load_snapshot(CACHE_OK))
    assert ok and problems == []
    ok, problems = fleet_verdict(load_snapshot(CACHE_BAD))
    assert not ok
    assert any("cache accounting broken" in p for p in problems)
    # a size below min pages the other way too
    snap = load_snapshot(ELASTIC_OK)
    snap["autoscaler"]["size"] = 0
    ok, problems = fleet_verdict(snap)
    assert not ok and any("below min" in p for p in problems)
    # the OK artifact carries the scale ledger in its /metrics text:
    # the labelled counter and both gauges are pinned against drift
    doc = json.load(open(ELASTIC_OK))
    assert 'serve_scale_events_total{direction="up"' in doc["metrics"]
    assert "serve_fleet_size 2" in doc["metrics"]
    assert "serve_standby_ready 1" in doc["metrics"]


def test_artifacts_validate_as_library_too():
    """Belt to the CLI suspenders: the library entry points the tests
    and the serve bench use agree with the CLIs."""
    from tools.check_slo import load_events, slo_report
    from tools.check_traces import parse_stream_text, validate

    trace = json.load(open(TRACE))
    assert validate(trace) == []
    streamed, truncated, errors = parse_stream_text(open(TELEMETRY).read())
    assert errors == [] and not truncated
    assert validate(streamed) == []
    names = {ev["name"] for ev in streamed["traceEvents"]}
    assert {"slo_alert", "slo_resolve", "prefill", "decode_burst"} <= names

    from ddp_practice_tpu.serve.slo import SLOConfig

    records, _ = load_events(TELEMETRY)
    report = slo_report(records, SLOConfig.from_json(TIGHT_SLO))
    assert not report["ok"] and report["trips"] == 1
    assert {r["kind"] for r in records} >= {
        "flight", "metrics", "alert", "span", "meta",
    }


# ------------------------------------------- ISSUE 8: fleet trace artifact
# a REAL 2-worker SIGKILL run's merged timeline (cli.py serve --procs 2
# --fault-plan kill --trace-out): router dispatch/failover instants plus
# worker-streamed spans under pid=worker-N lanes, clock_offset skew
# model stamped by the collector
FLEET_TRACE = os.path.join(ROOT, "tests", "data", "fleet_trace.json")
# bench-regression ledger pair: baseline == the repo's own
# BENCH_serve.json at the time the ledger was cut; _bad is the same
# file with a 1.5x-regressed seam latency ratio and 2 lost requests
BENCH_BASELINE = os.path.join(ROOT, "tests", "data",
                              "bench_baseline.json")
BENCH_BAD = os.path.join(ROOT, "tests", "data", "bench_current_bad.json")


def test_check_traces_fleet_mode_exit_codes_both_ways(tmp_path):
    # the merged 2-worker chaos timeline validates clean in fleet mode
    r = _run("tools/check_traces.py", "--fleet", FLEET_TRACE)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    # break causality: shift every router dispatch instant 1s LATER so
    # each precedes nothing — fleet mode must fail where plain validate
    # still passes (instants have no lane ordering of their own)
    trace = json.load(open(FLEET_TRACE))
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "i" and ev.get("name") == "dispatch":
            ev["ts"] += 1_000_000
    bad = tmp_path / "bad_fleet.json"
    bad.write_text(json.dumps(trace))
    assert _run("tools/check_traces.py", str(bad)).returncode == 0
    r = _run("tools/check_traces.py", "--fleet", str(bad))
    assert r.returncode == 1
    assert "causality" in r.stdout


def test_fleet_trace_artifact_contracts():
    """The artifact itself keeps the merge contract visible: worker
    lanes, a measured skew model, and failover trace_id linkage."""
    from tools.check_traces import measured_skew, validate_fleet

    trace = json.load(open(FLEET_TRACE))
    assert validate_fleet(trace) == []
    ev = trace["traceEvents"]
    lanes = {e["args"]["name"] for e in ev
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"router", "worker-0", "worker-1"} <= lanes
    skew = measured_skew(trace)
    assert skew and all(b < 0.05 for b in skew.values())
    fo = [e for e in ev if e.get("ph") == "i" and e["name"] == "failover"]
    assert fo, "the chaos artifact must contain a failover"
    # at least one migrated request's spans span BOTH worker lanes
    linked = False
    for e in fo:
        tid = e["args"]["trace_id"]
        pids = {x.get("pid") for x in ev
                if (x.get("args") or {}).get("trace_id") == tid
                or x.get("id") == tid}
        linked = linked or ({0, 1} <= pids)
    assert linked


def test_check_bench_exit_codes_both_ways(tmp_path):
    # the repo's OWN bench json vs the checked-in baseline: the ledger
    # that keeps fleet-overhead/goodput numbers honest across PRs
    r = _run("tools/check_bench.py", "BENCH_serve.json",
             "--baseline", BENCH_BASELINE)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "BENCH OK" in r.stdout
    # the regressed current fails, and names the regressed keys
    r = _run("tools/check_bench.py", BENCH_BAD,
             "--baseline", BENCH_BASELINE)
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout
    assert "latency_ratio_p50" in r.stdout
    assert "lost" in r.stdout
    # the ISSUE-12 observability gates regress in the same ledger: a
    # blown push overhead and a controller that missed its ±20% budget
    assert "otlp_push_overhead_100rps.mean_ratio" in r.stdout
    assert "adaptive_sampling_100rps.within_budget" in r.stdout
    # the ISSUE-13 spec-decode gates regress in the same ledger: an
    # evaporated TPOT win and one divergent stream — token identity
    # is an absolute contract (baseline 1.0, tol 0), so the planted
    # 31/32 identity must fail, not drift
    assert "spec_decode_8rps.tpot_ratio" in r.stdout
    assert "spec_decode_8rps.token_identity" in r.stdout
    # the ISSUE-14 elastic gates regress in the same ledger: the
    # goodput-per-worker edge evaporated, two requests lost across a
    # scale event, a reaction outside the evaluation window, a thrash
    # past the hold bound, and a 16s "warm" promotion — the absolute
    # seconds bound (baseline 0 -> limit = tol) must catch it
    assert "autoscale_burst_100rps.goodput_per_worker_ratio" in r.stdout
    assert "autoscale_burst_100rps.lost" in r.stdout
    assert "autoscale_burst_100rps.reaction_within_window" in r.stdout
    assert "autoscale_burst_100rps.oscillation_ok" in r.stdout
    assert "autoscale_burst_100rps.promote_join_s" in r.stdout
    # the ISSUE-15 cache-routing gates regress in the same ledger: the
    # affinity edge evaporated (hit-rate AND goodput ratios below the
    # band), two requests lost, and one stream diverged from the
    # least-loaded arm — identity is an absolute contract (baseline
    # 1.0, tol 0), so the planted 0.958 must fail, not drift
    assert "cache_routing_100rps.hit_rate_ratio" in r.stdout
    assert "cache_routing_100rps.goodput_ratio" in r.stdout
    assert "cache_routing_100rps.lost" in r.stdout
    assert "cache_routing_100rps.token_identity" in r.stdout
    # the ISSUE-19 tenant-QoS gates regress in the same ledger: a
    # FIFO-grade fairness index, an isolation ratio past the 0.7x
    # acceptance bound (gated as the 0/1 isolation_ok verdict), a
    # silent hostile alert next to a paging compliant tenant, a
    # diverged stream in each arm, and lost work under SIGKILL — the
    # 0/1 contracts are absolute, so every planted value must fail
    assert "qos_mixed_tenants_100rps.isolation_ok" in r.stdout
    assert "qos_mixed_tenants_100rps.fairness_index" in r.stdout
    assert "qos_mixed_tenants_100rps.hostile_alert_tripped" in r.stdout
    assert "qos_mixed_tenants_100rps.compliant_clean" in r.stdout
    assert "qos_mixed_tenants_100rps.token_identity" in r.stdout
    assert "qos_mixed_tenants_100rps.sigkill.check_qos_ok" in r.stdout
    assert "qos_mixed_tenants_100rps.sigkill.trace_ok" in r.stdout
    assert "qos_mixed_tenants_100rps.sigkill_lost" in r.stdout
    # unreadable input is exit 2, not a fake verdict
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{broken")
    assert _run("tools/check_bench.py", str(garbage)).returncode == 2
    assert _run("tools/check_bench.py",
                str(tmp_path / "missing.json")).returncode == 2
    # a custom gate map overrides the defaults (and --json round-trips)
    gates = tmp_path / "gates.json"
    gates.write_text(json.dumps({
        "fleet_x2_sigkill_100rps.fleet.lost":
            {"direction": "lower", "tol": 0.0},
    }))
    r = _run("tools/check_bench.py", BENCH_BAD, "--baseline",
             BENCH_BASELINE, "--gates", str(gates), "--json")
    assert r.returncode == 1
    rep = json.loads(r.stdout)
    assert [row["status"] for row in rep["rows"]] == ["regression"]


def test_check_bench_as_library():
    from tools.check_bench import bench_verdict, dig

    cur = json.load(open(os.path.join(ROOT, "BENCH_serve.json")))
    base = json.load(open(BENCH_BASELINE))
    ok, rows = bench_verdict(cur, base)
    assert ok, [r for r in rows
                if r["status"] not in ("ok", "skipped", "new")]
    # a key absent from BOTH sides is SKIPPED; one measured in current
    # with no baseline history is NEW (passes with a note — landing a
    # new bench entry must not require hand-editing old baselines);
    # one that vanished from current is a miss
    ok, rows = bench_verdict(
        cur, base, {"nonexistent.key": {"direction": "lower",
                                        "tol": 0.1}})
    assert ok and rows[0]["status"] == "skipped"
    ok, rows = bench_verdict(
        {"brand": {"new_metric": 1.23}}, base,
        {"brand.new_metric": {"direction": "lower", "tol": 0.1}})
    assert ok and rows[0]["status"] == "new" and "note" in rows[0]
    ok, rows = bench_verdict(
        {}, base, {"fleet_x2_overhead_8rps.latency_ratio_p50":
                   {"direction": "lower", "tol": 0.1}})
    assert not ok and rows[0]["status"] == "missing"
    assert dig({"a": {"b": 3}}, "a.b") == 3


def test_check_stream_exit_codes_both_ways(tmp_path):
    """The exactly-once audit over its checked-in artifact pair: the
    real chaos run (resume markers included) passes, the corrupted
    copy fails on BOTH planted violations, garbage is UNREADABLE (2) —
    a broken audit input must never read as a broken stream."""
    r = _run("tools/check_stream.py", STREAM_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "STREAMS OK" in r.stdout
    assert "VIOLATION" not in r.stdout

    r = _run("tools/check_stream.py", STREAM_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "STREAM CONTRACT BROKEN" in r.stdout
    assert "duplicate seq" in r.stdout          # the replayed line
    assert "no terminal marker" in r.stdout     # the silenced ending

    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text("{not json\n")
    assert _run("tools/check_stream.py", str(garbage)).returncode == 2
    assert _run("tools/check_stream.py",
                str(tmp_path / "missing.jsonl")).returncode == 2
    # a telemetry file with no chunk lines at all is a VIOLATION, not a
    # silent pass (wrong file / streaming was off)
    empty = tmp_path / "nochunks.jsonl"
    empty.write_text('{"kind": "flight", "rid": 0}\n')
    r = _run("tools/check_stream.py", str(empty))
    assert r.returncode == 1 and "no chunk lines" in r.stdout

    # --json emits the machine-readable verdict
    r = _run("tools/check_stream.py", "--json", STREAM_OK)
    v = json.loads(r.stdout)
    assert v["ok"] is True and v["streams"] > 0


# --------------------------------------- ISSUE 11: OTLP artifact pair
# a deterministic SAMPLED mini-fleet timeline (1% head rate): one
# head-sampled request (r64 — a crc32 pin, see utils/trace.head_keep),
# one tail-kept failover (r3), two clean suppressed requests — exported
# BOTH ways from one recorder, so the pair must round-trip forever;
# _bad is the OTLP form with one planted instance of every failure
# class the validator names (bad hex, int timestamp, duplicate spanId,
# orphaned parent)
OTLP_OK = os.path.join(ROOT, "tests", "data", "otlp_trace.json")
OTLP_CHROME = os.path.join(ROOT, "tests", "data",
                           "otlp_trace_chrome.json")
OTLP_BAD = os.path.join(ROOT, "tests", "data", "otlp_trace_bad.json")


def test_check_otlp_exit_codes_both_ways(tmp_path):
    # the good export validates AND round-trips against its chrome twin
    r = _run("tools/check_otlp.py", OTLP_OK, "--chrome", OTLP_CHROME)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout and "round-trip" in r.stdout
    # the corrupted copy fails on every planted class, by name
    r = _run("tools/check_otlp.py", OTLP_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "INVALID" in r.stdout
    assert "lowercase hex" in r.stdout
    assert "digit-string" in r.stdout
    assert "duplicate spanId" in r.stdout
    assert "orphaned" in r.stdout
    # a round-trip mismatch is a failure even when both files are
    # individually well-formed (the chrome twin of a DIFFERENT run)
    r = _run("tools/check_otlp.py", OTLP_OK, "--chrome", TRACE)
    assert r.returncode == 1
    # unreadable input is exit 2, not a fake verdict
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{broken")
    assert _run("tools/check_otlp.py", str(garbage)).returncode == 2
    assert _run("tools/check_otlp.py",
                str(tmp_path / "missing.json")).returncode == 2
    # --json appends the machine-readable report after the verdict line
    r = _run("tools/check_otlp.py", "--json", OTLP_OK)
    assert r.returncode == 0
    rep = json.loads(r.stdout.split("\n", 1)[1])[0]
    assert rep["ok"] is True and rep["spans"] == 10
    assert rep["traces"] == 2


def test_check_otlp_sampling_metadata_in_artifact():
    """The checked-in export carries the sampling header as resource
    attributes — a collector can tell a 1%-sampled partial timeline
    from span loss without any side channel."""
    otlp = json.load(open(OTLP_OK))
    res = {kv["key"]: kv["value"] for kv in
           otlp["resourceSpans"][0]["resource"]["attributes"]}
    assert res["service.name"] == {"stringValue": "ddp-serve"}
    assert res["ddp.sampling.head_rate"] == {"doubleValue": 0.01}
    assert res["ddp.sampling.traces_suppressed"] == {"intValue": "2"}
    # ...and the chrome twin says the same thing in its metadata block
    chrome = json.load(open(OTLP_CHROME))
    assert chrome["metadata"]["sampling"]["head_rate"] == 0.01
    assert chrome["metadata"]["sampling"]["kept_reasons"] == {
        "failover": 1}


# ---------------------------------- ISSUE 12: push-capture artifacts
# what the stub OTLP collector wrote during a real at-least-once push
# run: one payload file per POST. The OK capture holds 3 payloads but
# only 2 batches — the middle batch was delivered, its 200 was dropped
# (the SIGKILL-shaped failure), and the retry landed a byte-identical
# duplicate that batch-id dedup must fold away. The BAD capture is the
# other failure: the SAME spans re-delivered under a fresh batch id (a
# drain that re-emits), which dedup cannot save — the merged export
# fails on duplicate spanIds.
OTLP_PUSH_OK = os.path.join(ROOT, "tests", "data",
                            "otlp_push_capture_ok")
OTLP_PUSH_BAD = os.path.join(ROOT, "tests", "data",
                             "otlp_push_capture_bad")


def test_check_otlp_push_capture_dir_both_ways(tmp_path):
    r = _run("tools/check_otlp.py", OTLP_PUSH_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    assert "1 duplicate(s)" in r.stdout          # the retried batch
    assert "2 batch(es) from 3 payload(s)" in r.stdout
    r = _run("tools/check_otlp.py", OTLP_PUSH_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "INVALID" in r.stdout
    assert "duplicate spanId" in r.stdout
    # an empty capture directory is unreadable input, not a clean pass
    empty = tmp_path / "empty_capture"
    empty.mkdir()
    assert _run("tools/check_otlp.py", str(empty)).returncode == 2
    # a payload that parses but isn't an export is named, and fails
    mixed = tmp_path / "mixed_capture"
    mixed.mkdir()
    (mixed / "batch-0000.json").write_text('{"not": "otlp"}')
    r = _run("tools/check_otlp.py", str(mixed))
    assert r.returncode == 1
    assert "not an OTLP export" in r.stdout
    # --json carries the batch accounting
    r = _run("tools/check_otlp.py", "--json", OTLP_PUSH_OK)
    assert r.returncode == 0
    rep = json.loads(r.stdout.split("\n", 1)[1])[0]
    assert rep["unique_batches"] == 2 and rep["duplicate_batches"] == 1


def test_check_otlp_push_capture_as_library():
    from tools.check_otlp import (load_push_capture, push_batch_id,
                                  validate_otlp)

    export, info = load_push_capture(OTLP_PUSH_OK)
    assert validate_otlp(export) == []
    assert info["files"] == 3 and info["unique_batches"] == 2
    assert info["duplicate_batches"] == 1 and info["errors"] == []
    # every surviving batch id is unique and pusher-stamped
    bids = set()
    for name in sorted(os.listdir(OTLP_PUSH_OK)):
        bids.add(push_batch_id(
            json.load(open(os.path.join(OTLP_PUSH_OK, name)))))
    assert len(bids) == 2  # 3 files, one duplicated id
    export, info = load_push_capture(OTLP_PUSH_BAD)
    errs = validate_otlp(export)
    assert any("duplicate spanId" in e for e in errs)


def test_check_durations_exit_codes(tmp_path):
    """ISSUE 11 satellite: the tier-1 duration auditor's verdicts
    pinned through its real CLI — fits (0), projects past the 870 s
    wrapper timeout (1), unreadable ledger (2)."""
    fits = tmp_path / "fits.json"
    fits.write_text(json.dumps({
        "markexpr": "not slow", "wall_s": 500.0, "budget_s": 870.0,
        "tests": {"tests/test_a.py::t1": 3.0,
                  "tests/test_b.py::t2": 12.5},
    }))
    r = _run("tools/check_durations.py", str(fits))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    # the 12.5 s test inside a 'not slow' run draws the marker warning
    assert "mark it" in r.stdout and "test_b" in r.stdout
    # ...which --strict-slow escalates to a failure
    assert _run("tools/check_durations.py", "--strict-slow",
                str(fits)).returncode == 1
    over = tmp_path / "over.json"
    over.write_text(json.dumps({
        "markexpr": "not slow", "wall_s": 900.0, "budget_s": 870.0,
        "tests": {"tests/test_a.py::t1": 880.0},
    }))
    r = _run("tools/check_durations.py", str(over))
    assert r.returncode == 1
    assert "OVER BUDGET" in r.stdout and "truncates" in r.stdout
    # no wall_s: projection falls back to padded sum
    nowall = tmp_path / "nowall.json"
    nowall.write_text(json.dumps({
        "markexpr": "not slow",
        "tests": {"tests/test_a.py::t1": 850.0},
    }))
    assert _run("tools/check_durations.py",
                str(nowall)).returncode == 1
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{broken")
    assert _run("tools/check_durations.py",
                str(garbage)).returncode == 2
    assert _run("tools/check_durations.py",
                str(tmp_path / "missing.json")).returncode == 2
    notledger = tmp_path / "notledger.json"
    notledger.write_text('{"tests": "oops"}')
    assert _run("tools/check_durations.py",
                str(notledger)).returncode == 2


# ------------------------------------ ISSUE 19: tenant QoS artifacts
# the qos bench's SIGKILL leg (fair fleet x2, hostile "bulk" flooding
# compliant "acme", one worker SIGKILLed mid-run), slimmed to the
# record kinds check_qos judges (flight/alert/instant — chunk and
# metrics-dump lines stripped for size); _bad is the same file with
# the burn-alert edge reattributed to the compliant tenant, which
# breaks BOTH isolation claims at once (a compliant trip appears, the
# hostile trip vanishes)
QOS_TELEMETRY = os.path.join(ROOT, "tests", "data",
                             "qos_telemetry.jsonl")
QOS_TELEMETRY_BAD = os.path.join(ROOT, "tests", "data",
                                 "qos_telemetry_bad.jsonl")
# federated snapshots with the /tenants rollup riding next to healthz:
# _ok is a near-even two-tenant split, _bad a starved tenant (Jain
# ~0.51) on an otherwise HEALTHY fleet — only --min-fairness pages it
QOS_FLEET_OK = os.path.join(ROOT, "tests", "data",
                            "fleet_healthz_qos_ok.json")
QOS_FLEET_BAD = os.path.join(ROOT, "tests", "data",
                             "fleet_healthz_qos_bad.json")
# the failure budget the artifact run was recorded against: 5x the
# steady-state 0.5s TTFT target, because a mid-run worker SIGKILL
# makes the steady-state budget unmeetable by ANY scheduler (see
# serve/bench.py qos_bench)
QOS_SLO = json.dumps({"ttft_p99_s": 2.5, "fast_window_s": 0.5,
                      "slow_window_s": 1.0})


def test_check_qos_exit_codes_both_ways(tmp_path):
    """ISSUE-19 satellite: the per-tenant verdict pinned through the
    real CLI over the checked-in SIGKILL-leg telemetry. exit 0 = every
    isolation claim held, 1 = a claim broke, 2 = unreadable input."""
    r = _run("tools/check_qos.py", "--slo", QOS_SLO, "--hostile",
             "bulk", "--min-fairness", "0.9", "--expect-hostile-trip",
             QOS_TELEMETRY)
    assert r.returncode == 0, r.stdout + r.stderr
    assert ": OK" in r.stdout
    assert "[hostile]" in r.stdout
    assert "violated (hostile, not judged)" in r.stdout
    # the corrupted copy fails BOTH isolation claims, by name
    r = _run("tools/check_qos.py", "--slo", QOS_SLO, "--hostile",
             "bulk", "--min-fairness", "0.9", "--expect-hostile-trip",
             QOS_TELEMETRY_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "QOS VIOLATED" in r.stdout
    assert "alert trip(s) on a compliant tenant" in r.stdout
    assert "no hostile tenant tripped" in r.stdout
    # without the hostile exemption the flooder's own pain pages too
    r = _run("tools/check_qos.py", "--slo", QOS_SLO, QOS_TELEMETRY)
    assert r.returncode == 1
    assert "violated ttft_p99" in r.stdout
    # unreadable input / bad --slo are exit 2, not a fake verdict
    assert _run("tools/check_qos.py", "--slo", QOS_SLO,
                str(tmp_path / "missing.jsonl")).returncode == 2
    assert _run("tools/check_qos.py", "--slo", "{not json",
                QOS_TELEMETRY).returncode == 2
    # --json carries the per-tenant reports + fairness
    r = _run("tools/check_qos.py", "--slo", QOS_SLO, "--hostile",
             "bulk", "--json", QOS_TELEMETRY)
    assert r.returncode == 0
    rep = json.loads(r.stdout)[QOS_TELEMETRY]
    assert rep["ok"] is True
    assert rep["fairness_index"] >= 0.9
    assert rep["tenants"]["bulk"]["hostile"] is True
    assert rep["tenants"]["acme"]["trips"] == 0


def test_check_qos_as_library():
    """qos_report() is the seam the bench's SIGKILL leg calls
    in-process — pinned on the same artifact the CLI sees, including
    the contended-window rule that makes the fairness number mean
    something (a drained run delivers everyone's totals eventually;
    only tokens finished before the last arrival show who was served
    during the fight)."""
    from ddp_practice_tpu.serve.slo import SLOConfig
    from tools.check_qos import qos_report
    from tools.check_slo import load_events

    records, truncated = load_events(QOS_TELEMETRY)
    assert not truncated
    rep = qos_report(records, SLOConfig.from_json(QOS_SLO),
                     hostile=["bulk"], min_fairness=0.9,
                     expect_hostile_trip=True)
    assert rep["ok"], rep["problems"]
    # the window bound bites: the flooder's full token count is far
    # larger than what it got during the contended window, and the
    # fairness verdict is computed over the latter
    bulk = rep["tenants"]["bulk"]
    assert bulk["window_tokens"] < bulk["output_tokens"]
    assert rep["service_tokens"]["bulk"] == bulk["window_tokens"]
    # per-tenant trips come from the live registry's attributed alert
    # edges in the stream, not offline recomputation
    assert bulk["trips"] == 1
    assert rep["tenants"]["acme"]["trips"] == 0
    # no flights at all is unreadable-grade, not an empty pass
    try:
        qos_report([], SLOConfig.from_json(QOS_SLO))
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_check_fleet_qos_exit_codes_both_ways():
    """ISSUE-19 satellite: the federated /tenants rollup rendered and
    judged. Without --min-fairness the rollup is a VIEW (the starved
    snapshot still exits 0 — every worker is healthy); with it, a
    collapsed Jain's index pages even though no worker is sick,
    because a starved tenant is an outage for THAT tenant."""
    r = _run("tools/check_fleet.py", QOS_FLEET_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "tenants (fleet rollup, fairness index" in r.stdout
    assert "acme" in r.stdout and "bulk" in r.stdout
    assert "ttft p99" in r.stdout
    r = _run("tools/check_fleet.py", QOS_FLEET_BAD)
    assert r.returncode == 0, r.stdout + r.stderr  # view only
    r = _run("tools/check_fleet.py", "--min-fairness", "0.9",
             QOS_FLEET_OK)
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run("tools/check_fleet.py", "--min-fairness", "0.9",
             QOS_FLEET_BAD)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FLEET UNHEALTHY" in r.stdout
    assert "most-starved tenant: acme" in r.stdout
    # asking for the fairness judgment on a fleet that publishes no
    # rollup is a misconfigured probe, not a silent pass
    r = _run("tools/check_fleet.py", "--min-fairness", "0.9", FLEET_OK)
    assert r.returncode == 1
    assert "no /tenants rollup" in r.stdout
    # --json carries the rollup summary for machine consumers
    r = _run("tools/check_fleet.py", "--json", QOS_FLEET_BAD)
    assert r.returncode == 0
    rep = json.loads(r.stdout)[QOS_FLEET_BAD]
    assert rep["tenants"]["names"] == ["acme", "bulk"]
    assert rep["tenants"]["fairness_index"] < 0.6


def test_check_fleet_qos_verdict_as_library():
    from tools.check_fleet import load_snapshot_doc, tenant_problems

    _hz, _fl, tenants = load_snapshot_doc(QOS_FLEET_OK)
    assert tenant_problems(tenants, 0.9) == []
    assert tenant_problems(tenants, 0.0) == []  # 0 disables
    _hz, _fl, bad = load_snapshot_doc(QOS_FLEET_BAD)
    probs = tenant_problems(bad, 0.9)
    assert probs and "most-starved tenant: acme" in probs[0]
    assert tenant_problems(None, 0.9)  # no rollup + gate = problem
    # the rollup's pooled percentiles federate per the /flight rule —
    # the snapshot's p99 must come from the pooled samples, never a
    # percentile of percentiles
    assert tenants["tenants"]["acme"]["ttft_s"]["p99"] > 0


def test_check_stream_as_library():
    """stream_verdict() is the pure seam the bench's chaos rep calls
    in-process — pinned on the same artifacts the CLI sees."""
    sys.path.insert(0, ROOT)
    try:
        from tools.check_stream import load_jsonl, stream_verdict
    finally:
        sys.path.pop(0)
    ok, report = stream_verdict(load_jsonl(STREAM_OK))
    assert ok and not report["violations"]
    assert report["streams"] == 5 and report["tokens"] == 40
    ok, report = stream_verdict(load_jsonl(STREAM_BAD))
    assert not ok
    assert set(report["violations"]) == {"r0", "r3"}
