"""Tier-1 runtime-budget sentinel (runs LAST by alphabetical order).

The tier-1 gate wraps pytest in ``timeout -k 10 870`` — a suite that
outgrows the budget is TRUNCATED, and truncation reads as "fewer dots",
not as a failure. This file is the in-run alarm: z-named so the
``-p no:randomly`` alphabetical collection order schedules it after
every other test, when the conftest duration ledger is complete, it
projects the full-session wall time and fails LOUDLY while there is
still budget left to report in.

Offline twin: tools/check_durations.py audits the JSON ledger the
conftest writes at sessionfinish (env ``DDP_T1_DURATIONS_OUT``,
default /tmp/_t1_durations.json) — same projection, same budget.
"""

import pytest

# the tier-1 wrapper's hard timeout (also in conftest.T1_BUDGET_S;
# tests/ is not a package, so the constant is repeated, not imported)
T1_BUDGET_S = 870.0

# projection model: summed per-test durations undercount collection,
# imports, and fixture teardown still to come — pad by 5% plus a flat
# tail allowance before comparing against the hard timeout
OVERHEAD_FACTOR = 1.05
TAIL_ALLOWANCE_S = 45.0
# a partial run (-k, a single file) proves nothing about the suite;
# only audit when the ledger looks like the real tier-1 population
MIN_REPORTS = 100


def test_t1_suite_fits_the_timeout(request, t1_duration_ledger):
    markexpr = getattr(request.config.option, "markexpr", "") or ""
    if "not slow" not in markexpr.replace("(", "").replace(")", ""):
        pytest.skip("budget sentinel audits only the tier-1 "
                    "(-m 'not slow') run")
    if len(t1_duration_ledger) < MIN_REPORTS:
        pytest.skip(f"partial run ({len(t1_duration_ledger)} reports "
                    f"< {MIN_REPORTS}) — not the tier-1 population")
    total = sum(t1_duration_ledger.values())
    projected = total * OVERHEAD_FACTOR + TAIL_ALLOWANCE_S
    slowest = sorted(t1_duration_ledger.items(),
                     key=lambda kv: -kv[1])[:10]
    detail = "\n".join(f"  {d:7.2f}s  {n}" for n, d in slowest)
    assert projected < T1_BUDGET_S, (
        f"tier-1 projects to {projected:.0f}s against the hard "
        f"{T1_BUDGET_S:.0f}s timeout ({total:.0f}s measured across "
        f"{len(t1_duration_ledger)} tests) — the timeout TRUNCATES "
        f"silently, so shed load now: mark the slowest tests "
        f"@pytest.mark.slow (>10 s belongs there).\nslowest:\n{detail}"
    )


# the in-run marker gate hard-fails only past this multiple of the 10 s
# line: this 1-core box shows >2x run-to-run variance on individual
# tests (7.8 s and 18.4 s for the SAME test in back-to-back clean
# runs), so a test in the 1x-2x band is load noise, not a budget
# threat — it surfaces as a pytest warning instead of flapping tier-1.
# The PR-9-class offenders this gate exists for ran 12-188 s each,
# far past any noise band. tools/check_durations.py --strict-slow
# stays EXACT at 10 s for offline audits on quiet machines.
NOISE_MARGIN = 2.0


def test_t1_no_unmarked_slow_tests(request, t1_duration_ledger):
    """The marker contract, enforced in-run: any test over 10 s inside
    the tier-1 (``not slow``) population belongs behind
    ``@pytest.mark.slow``. This is tools/check_durations.py
    ``--strict-slow`` wired into the suite itself — the offline auditor
    only runs when someone remembers to, and an unmarked 30 s test
    erodes the 870 s budget three PRs before the projection sentinel
    above starts failing. Same ``audit()`` code path, so the CLI and
    the in-run gate cannot drift on what counts as an offender; the
    in-run gate only adds the NOISE_MARGIN band above."""
    import warnings as warnings_mod

    from tools.check_durations import SLOW_MARK_S, audit

    markexpr = getattr(request.config.option, "markexpr", "") or ""
    if "not slow" not in markexpr.replace("(", "").replace(")", ""):
        pytest.skip("marker-hygiene sentinel audits only the tier-1 "
                    "(-m 'not slow') run")
    if len(t1_duration_ledger) < MIN_REPORTS:
        pytest.skip(f"partial run ({len(t1_duration_ledger)} reports "
                    f"< {MIN_REPORTS}) — not the tier-1 population")
    ledger = dict(t1_duration_ledger)
    errors, warnings, _ = audit({
        "markexpr": markexpr,
        "tests": ledger,
    })
    assert not errors, "\n".join(errors)
    hard_line = SLOW_MARK_S * NOISE_MARGIN
    hard = [w for w in warnings
            if ledger.get(w.split(" took", 1)[0], 0.0) > hard_line]
    for w in warnings:
        if w not in hard:
            warnings_mod.warn(
                f"near the tier-1 slow line (noise band "
                f"{SLOW_MARK_S:.0f}-{hard_line:.0f}s): {w}")
    assert not hard, (
        f"{len(hard)} unmarked test(s) over {hard_line:.0f}s "
        f"({NOISE_MARGIN:.0f}x the {SLOW_MARK_S:.0f}s line — past any "
        "load-noise band) inside the tier-1 run — each line below is "
        "a one-line @pytest.mark.slow diff:\n  " + "\n  ".join(hard)
    )
