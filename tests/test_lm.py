"""Decoder-only LM family (models/lm.py) — the long-context flagship.

The reference has no sequence dimension at all (SURVEY §5.7); the LM is
where the framework's long-context machinery (causal attention, flash
kernel, ring/Ulysses sequence parallelism) composes into a trainable
model. Pinned here: causality (future tokens cannot leak), learnability
(next-token loss drops on a deterministic task), and SP composition
(seq-sharded decoder == unsharded decoder on the same params).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import (
    batch_sharding,
    build_mesh,
    replicated,
    shard_state,
)
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train.state import create_state, make_optimizer
from ddp_practice_tpu.train.steps import make_lm_train_step


def _tiny_lm(**kw):
    kw.setdefault("vocab_size", 32)
    kw.setdefault("max_len", 64)
    kw.setdefault("hidden_dim", 64)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 128)
    return create_model("lm_tiny", **kw)


@pytest.mark.fast
def test_lm_forward_shapes_and_dtype(devices):
    model = _tiny_lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, 32)
    # logits ride the policy compute dtype (fp32 here — the default
    # policy); under bf16 they stay bf16 and the CE upcasts per-element
    # inside its fused reductions (models/lm.py return comment).
    assert logits.dtype == jnp.float32


@pytest.mark.fast
def test_lm_is_causal(devices):
    """Perturbing token t must not change logits at positions < t."""
    model = _tiny_lm()
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, (1, 16)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    base = model.apply(variables, tokens)
    t = 10
    perturbed = tokens.at[0, t].set((int(tokens[0, t]) + 7) % 32)
    out = model.apply(variables, perturbed)
    np.testing.assert_array_equal(
        np.asarray(base[:, :t]), np.asarray(out[:, :t])
    )
    # and the perturbation IS visible at t (the model isn't degenerate)
    assert not np.allclose(np.asarray(base[:, t]), np.asarray(out[:, t]))


def test_lm_learned_positions_are_used(devices):
    """The position table must actually enter the forward pass (a refactor
    once dropped the add in non-decode mode; causality tests can't see it)."""
    model = _tiny_lm()
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 32, (1, 12)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), tokens)
    base = model.apply(variables, tokens)
    zeroed = jax.tree_util.tree_map_with_path(
        lambda p, leaf: jnp.zeros_like(leaf)
        if "pos_embed" in jax.tree_util.keystr(p) else leaf,
        variables["params"],
    )
    out = model.apply({"params": zeroed}, tokens)
    assert not np.allclose(np.asarray(base), np.asarray(out))


def test_lm_tied_embeddings(devices):
    """Weight tying: no lm_head param; logits == x @ tok_embed.T (pinned
    against a manual matmul on the same activations); grads flow into the
    shared table from both uses; cached decode still matches full forward."""
    from ddp_practice_tpu.inference import make_cache

    model = _tiny_lm(tied_embeddings=True)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 32, (2, 10)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), tokens)
    assert "lm_head" not in variables["params"]
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 10, 32)

    untied = _tiny_lm()
    uv = untied.init(jax.random.PRNGKey(0), tokens)
    n_tied = sum(x.size for x in jax.tree.leaves(variables["params"]))
    n_untied = sum(x.size for x in jax.tree.leaves(uv["params"]))
    assert n_untied - n_tied == 64 * 32  # the bias-free lm_head kernel

    g = jax.grad(
        lambda p: jnp.sum(model.apply({"params": p}, tokens) ** 2)
    )(variables["params"])
    emb_grad = g["tok_embed"]["embedding"]
    assert float(jnp.max(jnp.abs(emb_grad))) > 0

    # KV-cache decode parity (the tied head is position-independent, but
    # pin it anyway — the decode path shares the embed module instance)
    full = model.apply(variables, tokens)
    cache = make_cache(model, 2, 10)
    logits, mut = model.apply(
        {"params": variables["params"], "cache": cache},
        tokens[:, :4], decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :4]), rtol=2e-5, atol=2e-5
    )


def test_lm_rejects_overlong_sequence(devices):
    model = _tiny_lm(max_len=16)
    tokens = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        model.init(jax.random.PRNGKey(0), tokens)


def test_lm_train_step_learns_successor_task(devices):
    """Deterministic next-token task (x -> x+1 mod V): loss must collapse
    and next-token accuracy must approach 1 within a few hundred steps."""
    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    model = _tiny_lm()
    cfg = TrainConfig(optimizer="adam", learning_rate=3e-3)
    tx = make_optimizer(cfg)
    B, S = 8, 17  # S+1 positions; per-step batch 8 over 8 devices

    def init_fn(r):
        return create_state(
            model, tx, rng=r, sample_input=jnp.zeros((B, S - 1), jnp.int32)
        )

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, None)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    bsh = batch_sharding(mesh)
    step = make_lm_train_step(
        model, tx, mesh=mesh, state_shardings=shardings, batch_shardings=bsh
    )
    rng = np.random.default_rng(0)
    first = last = None
    for i in range(300):
        start = rng.integers(0, 32, (B, 1))
        tokens = (start + np.arange(S)) % 32
        batch = {"tokens": jnp.asarray(tokens, jnp.int32)}
        state, metrics = step(state, batch)
        if i == 0:
            first = float(metrics["loss"])
        last = metrics
    set_current_mesh(None)
    assert float(last["loss"]) < first * 0.05, (first, float(last["loss"]))
    assert float(last["accuracy"]) > 0.95
    assert float(last["perplexity"]) < 1.5


def test_lm_remat_matches_no_remat(devices):
    """remat is a memory/FLOPs trade, not a math change: same params, same
    logits (to float noise) and gradients flow."""
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (2, 16)), jnp.int32
    )
    plain = _tiny_lm()
    remat = _tiny_lm(remat=True)
    variables = plain.init(jax.random.PRNGKey(0), tokens)
    a = plain.apply(variables, tokens)
    b = remat.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6, rtol=0)

    def loss(params, model):
        return jnp.sum(model.apply({"params": params}, tokens) ** 2)

    ga = jax.grad(lambda p: loss(p, plain))(variables["params"])
    gb = jax.grad(lambda p: loss(p, remat))(variables["params"])
    for x, y in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=1e-4, rtol=1e-4
        )


def test_chunked_lm_step_matches_per_step(devices):
    """K LM steps per dispatch == K calls of the per-step factory."""
    from ddp_practice_tpu.train.steps import make_chunked_lm_train_step

    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    try:
        model = _tiny_lm()
        cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
        tx = make_optimizer(cfg)
        B, S, K = 8, 17, 4

        def init_fn(r):
            return create_state(
                model, tx, rng=r, sample_input=jnp.zeros((B, S - 1), jnp.int32)
            )

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        shardings = shard_state(abstract, mesh, None)
        bsh = batch_sharding(mesh)
        step = make_lm_train_step(
            model, tx, mesh=mesh, state_shardings=shardings,
            batch_shardings=bsh,
        )
        chunk = make_chunked_lm_train_step(
            model, tx, num_steps=K, mesh=mesh, state_shardings=shardings,
            batch_shardings=bsh,
        )
        rng = np.random.default_rng(3)
        batches = [
            {"tokens": jnp.asarray(rng.integers(0, 32, (B, S)), jnp.int32)}
            for _ in range(K)
        ]
        s_ref = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
        for b in batches:
            s_ref, m_ref = step(s_ref, b)
        s_chunk = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
        stacked = {"tokens": jnp.stack([b["tokens"] for b in batches])}
        s_chunk, m_chunk = chunk(s_chunk, stacked)
        assert int(s_chunk.step) == int(s_ref.step) == K
        for a, b in zip(
            jax.tree.leaves(s_ref.params), jax.tree.leaves(s_chunk.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=0
            )
        np.testing.assert_allclose(
            float(m_chunk["loss"]), float(m_ref["loss"]), rtol=1e-5
        )
    finally:
        set_current_mesh(None)


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_lm_sequence_parallel_matches_dense(devices, sp_impl):
    """The seq-sharded decoder (causal ring / Ulysses attention inside the
    blocks) must match the unsharded decoder on the same params."""
    mesh = build_mesh(MeshConfig(data=1, seq=8))
    set_current_mesh(mesh)
    try:
        # 8 heads: ulysses scatters heads over the 8-way seq axis
        dense_model = _tiny_lm(num_heads=8)
        sp_model = _tiny_lm(
            num_heads=8, seq_axis=MeshConfig.AXIS_SEQ, sp_impl=sp_impl
        )
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, (2, 32)), jnp.int32
        )
        variables = dense_model.init(jax.random.PRNGKey(0), tokens)
        base = dense_model.apply(variables, tokens)
        sp = sp_model.apply(variables, tokens)
        np.testing.assert_allclose(
            np.asarray(sp), np.asarray(base), rtol=2e-4, atol=2e-4
        )
    finally:
        set_current_mesh(None)


def test_lm_tensor_parallel_rules_cover_all_kernels(devices):
    """Every large LM kernel (qkv/out/fc_in/fc_out/embed/lm_head) gets a
    'tensor' spec from the rules; norms/bias-like leaves replicate."""
    from jax.tree_util import keystr

    model = _tiny_lm()
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    rules = param_sharding_rules("lm_tiny")
    assert rules is not None
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    specced = {
        keystr(path): rules(path, leaf) for path, leaf in flat
    }
    sharded = [n for n, s in specced.items() if s is not None]
    for expect in ("tok_embed", "lm_head", "qkv", "fc_in", "fc_out"):
        assert any(expect in n for n in sharded), (expect, sharded)
    assert all("ln" not in n for n in sharded)


def test_lm_tp_numerics_match_replicated(devices):
    """lm_tiny under tensor=8 sharding == fully replicated numerics."""
    mesh = build_mesh(MeshConfig(data=1, tensor=8))
    set_current_mesh(mesh)
    try:
        model = _tiny_lm(num_heads=8)  # heads divide the tensor axis
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 32, (2, 16)), jnp.int32
        )
        variables = model.init(jax.random.PRNGKey(0), tokens)
        base = model.apply(variables, tokens)

        rules = param_sharding_rules("lm_tiny")
        shardings = shard_state(variables["params"], mesh, rules)
        sharded_params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), variables["params"], shardings
        )
        rep = replicated(mesh)

        @jax.jit
        def fwd(params, tokens):
            return model.apply({"params": params}, tokens)

        out = fwd(sharded_params, jax.device_put(tokens, rep))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(base), rtol=2e-4, atol=2e-4
        )
    finally:
        set_current_mesh(None)
