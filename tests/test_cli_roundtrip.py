"""The user-visible journey, end to end in-process: train an LM through
the cli.py entry point, then sample from the checkpoint through the
generate.py entry point — the two commands a user actually types.

Covers the seams unit tests miss: argparse → TrainConfig wiring, the
checkpoint manifest roundtrip (model/optimizer/seq_len/vocab/pos_emb/
tied/clip recorded at save, rebuilt blind at generate time), and stdout
as the contract surface."""

import json

import pytest

from ddp_practice_tpu import generate as generate_cli
from ddp_practice_tpu import cli


def _train(tmp_path, capsys, *extra):
    argv = [
        "--model", "lm_tiny", "--dataset", "synthetic_tokens",
        "--seq_len", "48", "-e", "1", "-b", "4", "--max_steps", "8",
        "--optimizer", "adamw", "--lr", "1e-3",
        "--ckpt_dir", str(tmp_path / "ck"), "--log_every", "0", "--json",
        *extra,
    ]
    assert cli.main(argv) == 0
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_train_then_generate_roundtrip(tmp_path, capsys, devices):
    summary = _train(
        tmp_path, capsys,
        "--pos_emb", "rope", "--tied", "--clip_norm", "1.0",
    )
    assert summary["steps"] == 8
    assert "perplexity" in summary

    rc = generate_cli.main([
        "--ckpt_dir", str(tmp_path / "ck"),
        "--prompt", "ab", "--max_new_tokens", "6", "--temperature", "0",
    ])
    assert rc == 0
    # greedy generation is deterministic: a second run prints identical text
    first = capsys.readouterr().out
    generate_cli.main([
        "--ckpt_dir", str(tmp_path / "ck"),
        "--prompt", "ab", "--max_new_tokens", "6", "--temperature", "0",
    ])
    second = capsys.readouterr().out
    assert first == second


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_metrics_file_records_curves(tmp_path, capsys, devices):
    """--metrics_file: JSONL with per-step train records (monotone steps),
    an eval-derived record stream, and a final summary matching stdout."""
    mf = tmp_path / "m" / "metrics.jsonl"
    summary = _train(
        tmp_path, capsys,
        "--metrics_file", str(mf), "--log_every", "2", "--eval_every", "1",
    )
    records = [json.loads(l) for l in mf.read_text().splitlines()]
    kinds = [r["kind"] for r in records]
    assert kinds[-1] == "summary"
    eval_recs = [r for r in records if r["kind"] == "eval"]
    assert eval_recs and all("accuracy" in r for r in eval_recs)
    train_recs = [r for r in records if r["kind"] == "train"]
    assert train_recs and all("loss" in r and "time" in r for r in train_recs)
    steps = [r["step"] for r in train_recs]
    assert steps == sorted(steps)
    assert records[-1]["steps"] == summary["steps"]
    assert records[-1]["accuracy"] == summary["accuracy"]


@pytest.mark.fast
def test_generate_rejects_non_lm_checkpoint(tmp_path, capsys, devices):
    argv = [
        "--model", "convnet", "--dataset", "synthetic",
        "--synthetic_size", "64", "-e", "1", "-b", "8", "--max_steps", "4",
        "--ckpt_dir", str(tmp_path / "ck"), "--log_every", "0", "--json",
    ]
    assert cli.main(argv) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit, match="not an LM"):
        generate_cli.main(
            ["--ckpt_dir", str(tmp_path / "ck"), "--prompt", "x"]
        )
