"""Metrics registry (utils/metrics.py) + process-0 emission gate
(utils/logging.py emit_metrics) + serving adapter (serve/metrics.py).

The multi-host invariant pinned here: metric lines are a rank-0 side
effect like every other print/save in the framework — a non-0 process
calling emit_metrics produces NOTHING (no log record, None return), so
an N-host serving deployment emits one line per snapshot, not N.
"""

import logging

import pytest

from ddp_practice_tpu.utils.logging import emit_metrics, get_logger
from ddp_practice_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    labelled,
)


@pytest.mark.fast
def test_counter_gauge_histogram(devices):
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = Gauge()
    g.set(3)
    g.set(1.5)
    assert g.value == 1.5

    h = Histogram()
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100 and h.mean == pytest.approx(50.5)
    assert h.percentile(50) == pytest.approx(50.0, abs=1.0)
    assert h.percentile(99) == pytest.approx(99.0, abs=1.0)
    s = h.summary()
    assert s["count"] == 100 and "p99" in s


@pytest.mark.fast
def test_histogram_reservoir_bounds_memory(devices):
    h = Histogram(max_samples=8)
    for v in range(1000):
        h.observe(float(v))
    assert h.count == 1000            # exact count survives the bound
    assert h.sum == pytest.approx(sum(range(1000)))
    assert len(h._samples) == 8       # reservoir stays bounded
    # quantiles reflect recent traffic (the last ring-buffer writes)
    assert h.percentile(50) >= 900


@pytest.mark.fast
def test_registry_create_or_get_and_snapshot(devices):
    r = MetricsRegistry()
    assert r.counter("a") is r.counter("a")
    r.counter("a").inc(2)
    r.gauge("b").set(7)
    r.histogram("c").observe(1.0)
    snap = r.snapshot()
    assert snap["a"] == 2 and snap["b"] == 7
    assert snap["c_count"] == 1 and snap["c_mean"] == 1.0


@pytest.mark.fast
def test_emit_metrics_process0_gate(devices, monkeypatch, caplog):
    """Process 0 emits one line; any other process index emits nothing."""
    import jax

    # a name OUTSIDE the package hierarchy: get_logger("ddp_practice_tpu")
    # (created at import by train/elastic.py and friends) sets
    # propagate=False, so a child like ddp_practice_tpu.serve.* would
    # have its records swallowed at that parent before caplog's root
    # handler — whenever any train test is merely COLLECTED in the same
    # session, this test would flake on hierarchy, not on the gate
    logger = get_logger("serve_test_gate")
    logger.propagate = True  # let caplog's root handler see it

    with caplog.at_level(logging.INFO, logger="serve_test_gate"):
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        line = emit_metrics({"serve_tokens_total": 5}, logger)
        assert line.startswith("metrics ")
        assert '"serve_tokens_total": 5' in line
        assert any("serve_tokens_total" in r.message for r in caplog.records)

        caplog.clear()
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        assert emit_metrics({"serve_tokens_total": 5}, logger) is None
        assert not caplog.records


@pytest.mark.fast
def test_serve_metrics_report(devices):
    """The adapter names/types serving metrics and folds in tokens/sec."""
    from ddp_practice_tpu.serve.metrics import ServeMetrics
    from ddp_practice_tpu.serve.scheduler import Completion

    m = ServeMetrics()
    m.tokens_total.inc(40)
    m.on_complete(
        Completion(rid=0, tokens=[1, 2], status="eos", arrival=0.0,
                   finish=1.0, ttft=0.5, tpot=0.1),
        scheduler=None,
    )
    rep = m.report(elapsed_s=2.0)
    assert rep["serve_tokens_per_sec"] == pytest.approx(21.0)  # 42 / 2
    assert rep["serve_requests_eos"] == 1
    assert rep["serve_ttft_s_count"] == 1
    assert rep["serve_tpot_s_p50"] == pytest.approx(0.1)


@pytest.mark.fast
def test_paged_pool_metrics_export(devices):
    """The PR-6 pool observables (kv_blocks_in_use / kv_blocks_shared
    gauges, prefix-cache hit/miss token counters, preemptions_total)
    flow from the engine's cumulative fields into the registry as
    DELTAS per tick — and therefore onto /metrics (render_text) and the
    telemetry JSONL like every other metric. Host-pure via a stub
    engine mirroring PagedEngine's observable surface."""
    from ddp_practice_tpu.serve.metrics import ServeMetrics

    class _Blocks:
        num_blocks, num_used, num_shared, num_free = 9, 5, 2, 3

    class _Radix:
        hit_tokens, miss_tokens = 24, 8

        def evictable(self):
            return 1

    class _Alloc:
        max_slots = 4

    class _Eng:
        allocator = _Alloc()
        blocks = _Blocks()
        radix = _Radix()
        num_active = 2
        blocks_available = 4   # free + evictable
        preemptions = 3

    class _Sched:
        engine = _Eng()
        queue = ()

    m = ServeMetrics()
    m.on_tick(_Sched())
    rep = m.report()
    assert rep["kv_blocks_in_use"] == 5
    assert rep["kv_blocks_shared"] == 2
    assert rep["prefix_cache_hit_tokens_total"] == 24
    assert rep["prefix_cache_miss_tokens_total"] == 8
    assert rep["preemptions_total"] == 3
    # a second tick with no movement adds NOTHING (delta export, so the
    # counters stay counters even though the engine fields are gauges
    # of cumulative state)
    m.on_tick(_Sched())
    rep = m.report()
    assert rep["prefix_cache_hit_tokens_total"] == 24
    assert rep["preemptions_total"] == 3
    # and the names render on the Prometheus exposition
    text = m.registry.render_text()
    for name in ("kv_blocks_in_use", "kv_blocks_shared",
                 "prefix_cache_hit_tokens_total", "preemptions_total"):
        assert name in text


@pytest.mark.fast
def test_render_text_exposition(devices):
    """Prometheus text format: TYPE lines per family, labelled() names
    re-rendered as name{k="v"}, histograms as summaries with exact
    count/sum. Byte-stable ordering (families and label sets sorted)."""
    r = MetricsRegistry()
    r.counter("req_total").inc(7)
    r.counter(labelled("sheds_total", reason="brownout")).inc(2)
    r.counter(labelled("sheds_total", reason="queue_full")).inc()
    r.gauge(labelled("replica_state", replica=1)).set(2)
    h = r.histogram("ttft_s")
    for v in (0.1, 0.2, 0.4):
        h.observe(v)
    text = r.render_text()
    lines = text.splitlines()
    assert text.endswith("\n")
    assert "# TYPE req_total counter" in lines
    assert "req_total 7" in lines
    # one TYPE line per family, not per labelled child
    assert lines.count("# TYPE sheds_total counter") == 1
    i = lines.index("# TYPE sheds_total counter")
    # children sorted by rendered labels, values quoted
    assert lines[i + 1] == 'sheds_total{reason="brownout"} 2'
    assert lines[i + 2] == 'sheds_total{reason="queue_full"} 1'
    assert 'replica_state{replica="1"} 2' in lines
    assert 'ttft_s{quantile="0.5"} 0.2' in lines
    assert "ttft_s_count 3" in lines
    assert any(ln.startswith("ttft_s_sum 0.7") for ln in lines)
    # deterministic: same registry state -> identical bytes
    assert r.render_text() == text


@pytest.mark.fast
def test_render_text_escaping_and_label_ordering(devices):
    """Label values escape backslash/quote/newline; multi-label names
    render with keys sorted however the caller spelled the kwargs."""
    r = MetricsRegistry()
    r.counter(labelled("esc_total", path='say "hi"\nnow', d="a\\b")).inc()
    # same label SET spelled in the other kwarg order -> same metric
    r.counter(labelled("esc_total", d="a\\b", path='say "hi"\nnow')).inc()
    text = r.render_text()
    assert (
        'esc_total{d="a\\\\b",path="say \\"hi\\"\\nnow"} 2' in
        text.splitlines()
    )
