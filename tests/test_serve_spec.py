"""Speculative decoding (serve/spec.py + PagedEngine.step_verify).

The ISSUE-13 acceptance teeth, in three tiers:

- host-pure drafter units (tier-1 fast): the PromptLookupDraft index —
  longest-n priority, recency-wins, truncation at the context end, the
  trailing gram never matching itself — plus the config validations and
  the metrics/telemetry surfaces, none of which need a device;
- engine/scheduler pins (slow): greedy token-IDENTITY spec-vs-plain on
  a shared trace with zero new compiles under churn (the compile_guard
  fixture pins all five jitted-program counters, `verify_compiles`
  included), identity through block-aware preemption on an undersized
  pool, the PR-9 stream contract (a verified run = ONE seq-numbered
  chunk, tools/check_stream verdict clean), and per-request accept
  stats in the flight record;
- chaos (slow+chaos): SIGKILL a spec-enabled worker mid-stream and the
  spliced consumer streams still equal the fault-free plain oracle with
  zero duplicated / zero missing tokens.

Cross-run greedy identity on this image's XLA CPU inherits the
documented near-tie argmax flakiness (see test_serve_equivalence.py
_tolerate_load_flake) — identity pins retry the same trace: a real
verify/rollback bug diverges on every attempt.
"""

import types

import numpy as np
import pytest

from ddp_practice_tpu.serve.spec import DraftSource, PromptLookupDraft

VOCAB = 32


# ---------------------------------------------------------------- drafter
def test_drafter_validates_ngram_bounds():
    with pytest.raises(ValueError):
        PromptLookupDraft(ngram_max=0)
    with pytest.raises(ValueError):
        PromptLookupDraft(ngram_max=2, ngram_min=3)
    with pytest.raises(ValueError):
        PromptLookupDraft(ngram_max=3, ngram_min=0)


def test_drafter_basic_lookup_and_trailing_gram_never_self_matches():
    d = PromptLookupDraft(ngram_max=3, ngram_min=1)
    d.begin(0, [5, 6, 7, 5, 6, 7, 5, 6])
    # trailing (7, 5, 6) has one EARLIER occurrence at positions 2..4,
    # whose continuation starts at position 5 — [7, 5, 6] — and the
    # chained re-lookup of the new tail (7, 5, 6) fills the 4th token
    assert d.propose(0, 4) == [7, 5, 6, 7]
    # a context whose trailing gram appears nowhere earlier: no proposal
    d.begin(1, [1, 2, 3, 4])
    assert d.propose(1, 4) == []
    d.end(0)
    d.end(1)
    assert d.propose(0, 4) == []  # unknown slot: hint, not an error


def test_drafter_longest_ngram_wins():
    # trailing 2-gram (9, 2) matches position 2's occurrence; the
    # trailing 1-gram (2) alone would match a later, different spot —
    # the longer context must win
    d = PromptLookupDraft(ngram_max=3, ngram_min=1)
    d.begin(0, [9, 2, 8, 8, 2, 1, 9, 2])
    assert d.propose(0, 2) == [8, 8]


def test_drafter_recency_wins_between_equal_length_matches():
    # (4, 4) occurs twice with different continuations: 0->[7...] and
    # 4->[1...]; the index keeps the most recent, so the draft is [1, 5]
    d = PromptLookupDraft(ngram_max=2, ngram_min=1)
    d.begin(0, [4, 4, 7, 3, 4, 4, 1, 5, 4, 4])
    assert d.propose(0, 2) == [1, 5]


def test_drafter_chains_through_the_context_end():
    # the most recent earlier (5,6,7) match yields only 3 KNOWN
    # continuation tokens before the context ends — chaining re-matches
    # the draft's own tail and keeps going, so a k=4 ask is filled on
    # cyclic text instead of truncating (without chaining a period-p
    # cycle caps every draft at p tokens, and verify's fixed two-apply
    # dispatch never amortizes)
    d = PromptLookupDraft(ngram_max=3, ngram_min=1)
    d.begin(0, [5, 6, 7, 5, 6, 7, 5, 6, 7])
    assert d.propose(0, 4) == [5, 6, 7, 5]
    # no match at all still means no draft — chaining never invents one
    d.begin(1, [1, 2, 3])
    assert d.propose(1, 4) == []


def test_drafter_incremental_extend_equals_bulk_begin():
    rng = np.random.default_rng(0)
    ctx = rng.integers(0, 8, 40).tolist()
    bulk = PromptLookupDraft(3, 1)
    bulk.begin(0, ctx)
    inc = PromptLookupDraft(3, 1)
    inc.begin(0, ctx[:5])
    for t in ctx[5:]:
        inc.extend(0, [t])
    assert inc.snapshot(0) == bulk.snapshot(0) == ctx
    for k in (1, 3, 6):
        assert inc.propose(0, k) == bulk.propose(0, k)


def test_drafter_begin_resets_and_snapshot_tracks():
    d = PromptLookupDraft(2, 1)
    d.begin(0, [1, 2, 1])
    assert d.context_len(0) == 3
    d.begin(0, [7, 7])   # readmission: a fresh context, no stale grams
    assert d.snapshot(0) == [7, 7]
    # only the new context's (7)->7 gram exists; chaining rides it to k
    assert d.propose(0, 3) == [7, 7, 7]
    assert d.context_len(1) == -1
    # the DraftSource default snapshot (cold fork sibling) is empty
    assert DraftSource.snapshot(d, 0) == []


# ----------------------------------------------------- config validations
def _stub_model():
    return types.SimpleNamespace(pos_emb="rope", max_len=128)


def test_slot_engine_refuses_spec_decode():
    from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine

    with pytest.raises(ValueError, match="PagedEngine"):
        SlotEngine(_stub_model(), None,
                   EngineConfig(spec_decode=True))


def test_paged_engine_validates_spec_config():
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine

    with pytest.raises(ValueError, match="temperature"):
        PagedEngine(_stub_model(), None,
                    EngineConfig(spec_decode=True, temperature=0.7))
    with pytest.raises(ValueError, match="spec_k"):
        PagedEngine(_stub_model(), None,
                    EngineConfig(spec_decode=True, spec_k=0))


# ----------------------------------------------- metrics/telemetry surface
def test_serve_metrics_export_spec_counters_as_deltas():
    from ddp_practice_tpu.serve.metrics import ServeMetrics

    eng = types.SimpleNamespace(
        num_active=0,
        allocator=types.SimpleNamespace(max_slots=2),
        spec_drafted_tokens=10, spec_accepted_tokens=6,
    )
    sched = types.SimpleNamespace(queue=[], engine=eng)
    m = ServeMetrics()
    m.on_tick(sched)
    eng.spec_drafted_tokens, eng.spec_accepted_tokens = 25, 14
    m.on_tick(sched)
    snap = m.report()
    assert snap["spec_drafted_tokens_total"] == 25
    assert snap["spec_accepted_tokens_total"] == 14
    # engines without speculation keep the counters at zero, not absent
    plain = types.SimpleNamespace(
        num_active=0, allocator=types.SimpleNamespace(max_slots=2))
    m2 = ServeMetrics()
    m2.on_tick(types.SimpleNamespace(queue=[], engine=plain))
    assert m2.report()["spec_drafted_tokens_total"] == 0


def test_flight_stats_surface_spec_accept_rate():
    from ddp_practice_tpu.utils.telemetry import FlightStats

    fs = FlightStats()
    base = {"queue_s": 0.0, "prefill_s": 0.1, "decode_s": 0.4,
            "stall_s": 0.0}
    comp = types.SimpleNamespace(
        flight=dict(base, spec_drafted=8, spec_accepted=6,
                    spec_accept_rate=0.75),
        ttft=0.2, tpot=0.05, trace_id=None)
    fs.on_completion(comp)
    # mixed window: a non-spec flight lacks the key and must not break
    fs.on_completion(types.SimpleNamespace(
        flight=dict(base), ttft=0.3, tpot=0.06, trace_id=None))
    rep = fs.report()
    assert rep["spec_accept_rate"]["p50"] == 0.75
    assert rep["samples"]["spec_accept_rate"] == [0.75]


# ====================================================== engine-level pins
# everything below compiles real jitted programs (~15-25 s each on the
# CI CPU) — full-suite tier only, per the tier-1 870 s budget
slow = pytest.mark.slow


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _tolerate_load_flake(attempt, args_per_try):
    """Same contract as test_serve_equivalence.py: a deterministic
    verify/rollback bug fails every attempt; only the documented
    XLA-CPU near-tie argmax transient passes a replay."""
    for i, args in enumerate(args_per_try):
        try:
            return attempt(*args)
        except AssertionError:
            if i == len(args_per_try) - 1:
                raise


def _lookup_friendly_trace(rng, n=10):
    """Prompts with internal repetition (the prompt-lookup sweet spot):
    a short motif repeated with noise, so drafts actually fire."""
    out = []
    for i in range(n):
        motif = rng.integers(0, VOCAB, int(rng.integers(2, 4))).tolist()
        reps = int(rng.integers(2, 4))
        prompt = (motif * reps)[: int(rng.integers(4, 9))]
        out.append({
            "rid": i,
            "prompt": prompt,
            "max_new_tokens": int(rng.integers(2, 16)),
        })
    return out


def _run_trace(engine, trace, **sched_kw):
    from ddp_practice_tpu.serve.scheduler import (
        FakeClock,
        Request,
        Scheduler,
    )

    sched = Scheduler(engine, clock=FakeClock(), max_queue=len(trace),
                      **sched_kw)
    for t in trace:
        sched.submit(Request(**t))
    sched.run_until_idle()
    return sched


def _warm(eng):
    from ddp_practice_tpu.serve.engine import warm_engine

    warm_engine(eng)
    return eng


@slow
def test_spec_token_identity_and_zero_recompiles(devices, lm,
                                                 compile_guard):
    """THE tentpole pin: the spec-enabled paged engine is greedy
    token-identical to the plain paged engine on a shared scheduler
    trace (churn, EOS releases, verify dispatches and all), with zero
    new compiles after warmup — `verify_compiles` is pinned by the same
    compile_guard as every other program counter."""
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine

    model, params = lm

    def attempt(seed):
        trace = _lookup_friendly_trace(np.random.default_rng(seed))
        kw = dict(max_slots=3, prompt_buckets=(8,), eos_id=5,
                  block_size=8, max_blocks_per_slot=6)
        plain = _warm(PagedEngine(model, params, EngineConfig(**kw)))
        spec = _warm(PagedEngine(model, params, EngineConfig(
            spec_decode=True, spec_k=4, **kw)))
        assert spec.compile_stats()["verify_compiles"] == 1
        with compile_guard(plain, spec):
            got_plain = {
                c.rid: (c.status, tuple(c.tokens))
                for c in _run_trace(plain, trace).completions
            }
            got_spec = {
                c.rid: (c.status, tuple(c.tokens))
                for c in _run_trace(spec, trace).completions
            }
        assert got_spec == got_plain
        # the run really speculated: drafts fired and some were accepted
        assert spec.spec_dispatches > 0
        assert spec.spec_drafted_tokens > 0
        assert spec.spec_accepted_tokens > 0
        assert spec.spec_accepted_tokens <= spec.spec_drafted_tokens
        # rejected tails gave their blocks back: pool fully drained
        assert spec.blocks.num_free == spec.blocks.num_blocks - 1

    _tolerate_load_flake(attempt, [(11,), (11,)])


@slow
def test_spec_token_identity_through_preemption(devices, lm):
    """Speculation x block-aware preemption: an UNDERSIZED pool forces
    evictions mid-request; readmission re-prefills prompt + salvaged
    tokens (rebuilding drafter context from scratch) and the final
    streams still match a plain engine with an ample pool."""
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine

    model, params = lm

    def attempt(seed):
        trace = _lookup_friendly_trace(np.random.default_rng(seed), n=8)
        plain = _warm(PagedEngine(model, params, EngineConfig(
            max_slots=3, prompt_buckets=(8,), eos_id=5,
            block_size=8, max_blocks_per_slot=6)))
        spec = _warm(PagedEngine(model, params, EngineConfig(
            max_slots=3, prompt_buckets=(8,), eos_id=5,
            block_size=8, max_blocks_per_slot=6,
            # 6 real blocks for 3 slots x 6: growth (and the verify
            # program's k+1 up-front grow) must preempt under load —
            # chained drafts drain requests fast enough that a merely
            # snug pool never tightens
            num_blocks=7,
            spec_decode=True, spec_k=4)))
        got_plain = {
            c.rid: (c.status, tuple(c.tokens))
            for c in _run_trace(plain, trace).completions
        }
        got_spec = {
            c.rid: (c.status, tuple(c.tokens))
            for c in _run_trace(spec, trace).completions
        }
        assert got_spec == got_plain
        assert spec.preemptions > 0, "pool never tightened — dead pin"
        assert spec.spec_accepted_tokens > 0
        assert spec.blocks.num_free == spec.blocks.num_blocks - 1

    _tolerate_load_flake(attempt, [(7,), (7,)])


@slow
def test_spec_stream_contract_and_flight_records(devices, lm):
    """PR-9 contract with speculation on: a verified run reaches the
    stream as ONE seq-numbered TokenChunk (never one chunk per drafted
    token), offsets are contiguous, exactly one final chunk — the
    tools/check_stream verdict is clean — and every completion that
    drafted carries spec_drafted / spec_accepted / spec_accept_rate in
    its flight record."""
    from tools.check_stream import stream_verdict

    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine

    model, params = lm
    trace = _lookup_friendly_trace(np.random.default_rng(3), n=8)
    for t in trace:
        t["trace_id"] = f"tid-{t['rid']}"
    spec = _warm(PagedEngine(model, params, EngineConfig(
        max_slots=3, prompt_buckets=(8,), eos_id=5,
        block_size=8, max_blocks_per_slot=6,
        spec_decode=True, spec_k=4)))
    sched = _run_trace(spec, trace, stream=True)

    lines = [{
        "kind": "chunk", "trace_id": c.trace_id, "rid": c.rid,
        "seq": c.seq, "start": c.start, "n": len(c.tokens),
        "final": c.final,
    } for c in sched.chunks]
    ok, report = stream_verdict(lines)
    assert ok, report["violations"]
    assert report["streams"] == len(trace)
    # chunks reassemble to exactly the completion tokens (offset-keyed)
    by_rid = {c.rid: c for c in sched.completions}
    for rid, comp in by_rid.items():
        toks = []
        for ch in sched.chunks:
            if ch.rid == rid:
                assert ch.start == len(toks)
                toks.extend(ch.tokens)
        assert toks == list(comp.tokens)
    # a verified run rode ONE chunk: some chunk carries >1 token even
    # though decode_burst=1 would emit singletons without speculation
    assert spec.config.decode_burst == 1
    assert any(len(c.tokens) > 1 and not c.final for c in sched.chunks)
    # flight records: accept stats present, sane, and consistent with
    # the engine's cumulative counters
    flights = [c.flight for c in sched.completions]
    drafted = sum(f.get("spec_drafted", 0) for f in flights)
    accepted = sum(f.get("spec_accepted", 0) for f in flights)
    assert drafted == spec.spec_drafted_tokens
    assert accepted == spec.spec_accepted_tokens
    assert any("spec_accept_rate" in f for f in flights)
    for f in flights:
        if "spec_accept_rate" in f:
            assert 0.0 <= f["spec_accept_rate"] <= 1.0
            assert f["spec_accepted"] <= f["spec_drafted"]


@slow
def test_spec_respects_eos_inside_verified_run(devices, lm):
    """A verified run that crosses EOS must cut AT the EOS token, same
    as a plain burst: the scheduler walks verify rows through the same
    row loop, so acceptance never overshoots a request's end."""
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine

    model, params = lm

    def attempt(seed):
        rng = np.random.default_rng(seed)
        trace = _lookup_friendly_trace(rng, n=10)
        kw = dict(max_slots=3, prompt_buckets=(8,), eos_id=5,
                  block_size=8, max_blocks_per_slot=6)
        plain = _warm(PagedEngine(model, params, EngineConfig(**kw)))
        spec = _warm(PagedEngine(model, params, EngineConfig(
            spec_decode=True, spec_k=4, **kw)))
        got_plain = {c.rid: (c.status, tuple(c.tokens))
                     for c in _run_trace(plain, trace).completions}
        got_spec = {c.rid: (c.status, tuple(c.tokens))
                    for c in _run_trace(spec, trace).completions}
        assert got_spec == got_plain
        assert any(s == "eos" for s, _ in got_plain.values()), \
            "no request hit EOS — the pin pinned nothing"
        for rid, (status, toks) in got_spec.items():
            if status == "eos":
                assert toks[-1] == 5 and 5 not in toks[:-1]

    _tolerate_load_flake(attempt, [(23,), (23,)])


# ================================================= chaos: real SIGKILL
# speculation x process death: spawns real spec-enabled workers
# (test_worker_stream.py idiom) — slow + chaos.

WORKER_MODEL_KW = {"vocab_size": 64, "max_len": 64, "hidden_dim": 64,
                   "depth": 2, "num_heads": 4, "mlp_dim": 128,
                   "pos_emb": "rope"}
WORKER_ENGINE_KW = {"paged": True, "max_slots": 2,
                    "prompt_buckets": [8, 16], "temperature": 0.0,
                    "eos_id": None, "block_size": 8,
                    "max_blocks_per_slot": 8, "decode_burst": 4}


def _worker_trace(n=6, seed=5):
    """Lookup-friendly prompts (repeated motifs) in the worker vocab."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        motif = rng.integers(1, 64, int(rng.integers(2, 4))).tolist()
        prompt = (motif * 3)[: int(rng.integers(5, 9))]
        out.append({
            "rid": i,
            "prompt": prompt,
            "max_new_tokens": int(rng.integers(6, 10)),
        })
    return out


def _plain_oracle(trace):
    """Fault-free PLAIN (non-speculative) greedy oracle, in-process."""
    from ddp_practice_tpu.serve.engine import EngineConfig, PagedEngine
    from ddp_practice_tpu.serve.scheduler import Request, Scheduler
    from ddp_practice_tpu.serve.worker import build_model

    model, params = build_model(WORKER_MODEL_KW)
    kw = dict(WORKER_ENGINE_KW)
    kw.pop("paged")
    kw["prompt_buckets"] = tuple(kw["prompt_buckets"])
    engine = PagedEngine(model, params, EngineConfig(**kw))
    sched = Scheduler(engine, max_queue=64)
    for t in trace:
        sched.submit(Request(**t))
    comps = sched.run_until_idle()
    assert all(c.status == "length" for c in comps)
    return {c.rid: list(c.tokens) for c in comps}


@slow
@pytest.mark.chaos
def test_spec_sigkill_failover_exactly_once(tmp_path):
    """SIGKILL a spec-enabled worker mid-stream: every request finishes
    token-identical to the fault-free PLAIN oracle (speculation plus
    crash-migration are both invisible in the stream), consumer splices
    carry zero duplicated / zero missing tokens, migrated requests'
    merged flight records keep their accept stats, and the offline
    tools/check_stream.py audit passes the run's telemetry."""
    import json
    import os
    import subprocess
    import sys
    import time

    from ddp_practice_tpu.serve.scheduler import Request
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from ddp_practice_tpu.utils.telemetry import TelemetryExporter

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wspec = WorkerSpec(model=WORKER_MODEL_KW, engine=WORKER_ENGINE_KW,
                       max_queue=64, spec_decode=True, spec_k=4)
    sup_cfg = SupervisorConfig(restart_base_s=0.25, restart_budget=5,
                               ready_timeout_s=300.0)

    def attempt():
        trace = _worker_trace()
        expected = _plain_oracle(trace)
        tpath = str(tmp_path / "spec_stream.jsonl")
        exporter = TelemetryExporter(tpath, start=False)
        router, sup, handles = make_fleet_router(
            wspec, 2, sup_config=sup_cfg, telemetry=exporter
        )
        try:
            for t in trace:
                router.submit(Request(**t))
            deadline = time.monotonic() + 60
            while not (any(st["tokens"]
                           for st in handles[0].outstanding.values())
                       and any(s.delivered
                               for s in router.streams.values())):
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            sup.kill(0, "SIGKILL")
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            for rid, want in expected.items():
                c = by_rid[rid]
                st = router.stream(rid)
                assert c.tokens == want, f"rid {rid} diverged"
                assert st.tokens() == want, f"stream {rid} diverged"
                assert st.closed and st.status == "length"
                assert st.suppressed >= 0 and st.gaps == 0
            # the fleet really speculated: the router-merged flight
            # records carry accept stats home over RPC
            drafted = sum(c.flight.get("spec_drafted", 0)
                          for c in by_rid.values())
            assert drafted > 0, "no worker drafted — dead chaos pin"
            for c in by_rid.values():
                if c.flight.get("spec_drafted", 0):
                    assert 0.0 <= c.flight["spec_accept_rate"] <= 1.0
        finally:
            sup.stop()
            exporter.pump()
            exporter.close()
        r = subprocess.run(
            [sys.executable, "tools/check_stream.py", tpath],
            capture_output=True, text=True, cwd=root, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        report = [json.loads(x) for x in open(tpath) if x.strip()]
        assert any(ln.get("kind") == "chunk" for ln in report)

    _tolerate_load_flake(attempt, [(), ()])
