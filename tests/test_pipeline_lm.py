"""Pipelined decoder LM (models/pipeline_lm.py): GPipe over causal blocks.

Contract mirrors the ViT pipeline tests: the schedule reorders compute,
not math — pipelined forward/grads equal the depth-sequential apply of
the SAME stacked params; causality survives (microbatching splits the
batch, never the sequence); and the full LM train step runs with
stage+tensor-sharded params on a mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.ring import set_current_mesh
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train import create_state, make_optimizer
from ddp_practice_tpu.train.steps import make_lm_train_step

VOCAB = 32
KW = dict(vocab_size=VOCAB, max_len=32, hidden_dim=32, depth=4,
          num_heads=4, mlp_dim=64)


def _tokens(b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, VOCAB, (b, s)), jnp.int32)


def _partial_manual(fn, *args, **kwargs):
    """Same contract as tests/test_pipeline.py: this image's old XLA
    cannot compile a partial-manual shard_map (pipeline island x
    GSPMD-automatic 'tensor' axis) — "PartitionId instruction is not
    supported for SPMD partitioning" (ROADMAP standing debt). Skip on
    exactly that environment limit, fail on anything else."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:
        if "PartitionId" in str(e):
            pytest.skip("old XLA: PartitionId unsupported under "
                        "partial-manual SPMD partitioning")
        raise


@pytest.fixture()
def pipe_mesh(devices):
    mesh = build_mesh(MeshConfig(data=2, pipe=4))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


@pytest.mark.parametrize("pos_emb", ["learned", "rope"])
def test_pipelined_lm_forward_matches_sequential(pipe_mesh, pos_emb):
    piped = create_model("lm_pipe", num_stages=4, num_microbatches=2,
                         pos_emb=pos_emb, **KW)
    seq = create_model("lm_pipe", num_stages=1, pos_emb=pos_emb, **KW)
    tokens = _tokens()
    variables = seq.init(jax.random.PRNGKey(0), tokens)
    want = seq.apply(variables, tokens)
    got = piped.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_pipelined_lm_grads_match_sequential(pipe_mesh):
    piped = create_model("lm_pipe", num_stages=4, num_microbatches=2, **KW)
    seq = create_model("lm_pipe", num_stages=1, **KW)
    tokens = _tokens(seed=1)
    variables = seq.init(jax.random.PRNGKey(1), tokens)

    def loss(model, params):
        return jnp.sum(model.apply({"params": params}, tokens) ** 2)

    g_seq = jax.grad(lambda p: loss(seq, p))(variables["params"])
    g_pipe = jax.grad(lambda p: loss(piped, p))(variables["params"])
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        )


@pytest.mark.parametrize("microbatches", [2, 4])
def test_1f1b_loss_and_grads_match_sequential(pipe_mesh, microbatches):
    """The 1F1B schedule (parallel/pipeline_1f1b.py) computes the SAME
    mean loss, accuracy counts and grads as autodiff of the sequential
    model — interleaving reorders compute, not math. M=2 exercises a
    bubble-heavy schedule, M=4 the steady state."""
    piped = create_model("lm_pipe", num_stages=4, schedule="1f1b",
                         num_microbatches=microbatches, **KW)
    seq = create_model("lm_pipe", num_stages=1, **KW)
    tokens = _tokens(seed=5)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    variables = seq.init(jax.random.PRNGKey(2), tokens[:, :-1])

    from ddp_practice_tpu.ops.losses import accuracy_counts, cross_entropy

    def seq_loss(p):
        logits = seq.apply({"params": p}, inputs)
        return cross_entropy(logits, targets), logits

    (want_loss, want_logits), want_grads = jax.value_and_grad(
        seq_loss, has_aux=True
    )(variables["params"])
    want_correct, want_total = accuracy_counts(want_logits, targets)
    (loss, counts), grads = jax.jit(
        lambda p: piped.loss_and_grad(p, inputs, targets)
    )(variables["params"])

    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    assert float(counts["correct"]) == float(want_correct)
    assert float(counts["total"]) == float(want_total)
    flat_w, tdef = jax.tree_util.tree_flatten_with_path(want_grads)
    flat_g = jax.tree.leaves(grads)
    assert len(flat_w) == len(flat_g)
    for (path, w), g in zip(flat_w, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_1f1b_sharded_train_step(devices):
    """dp x pp x tp with the 1F1B schedule: the full train step (metrics,
    optimizer update) runs on sharded params and moves them."""
    mesh = build_mesh(MeshConfig(data=2, pipe=2, tensor=2))
    set_current_mesh(mesh)
    try:
        model = create_model("lm_pipe", num_stages=2, num_microbatches=2,
                             schedule="1f1b", **KW)
        cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
        tx = make_optimizer(cfg)
        B, S = 8, 17

        def init_fn(r):
            return create_state(
                model, tx, rng=r, sample_input=jnp.zeros((B, S - 1), jnp.int32)
            )

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        rules = param_sharding_rules("lm_pipe")
        shardings = shard_state(abstract, mesh, rules)
        state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
        bsh = batch_sharding(mesh)
        step = make_lm_train_step(
            model, tx, mesh=mesh, state_shardings=shardings,
            batch_shardings=bsh,
        )
        batch = {"tokens": _tokens(B, S, seed=6)}
        before = np.asarray(jax.device_get(
            jax.tree.leaves(state.params)[0]))
        state, metrics = _partial_manual(step, state, batch)
        assert np.isfinite(float(metrics["loss"]))
        after = np.asarray(jax.device_get(jax.tree.leaves(state.params)[0]))
        assert not np.allclose(before, after)
    finally:
        set_current_mesh(None)


def test_pipelined_lm_is_causal(pipe_mesh):
    """Perturbing token t must not change logits before t, THROUGH the
    pipeline schedule (microbatching splits batch, not sequence)."""
    model = create_model("lm_pipe", num_stages=4, num_microbatches=2, **KW)
    tokens = _tokens(b=4, seed=2)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    base = model.apply(variables, tokens)
    t = 9
    perturbed = tokens.at[0, t].set((int(tokens[0, t]) + 7) % VOCAB)
    out = model.apply(variables, perturbed)
    np.testing.assert_allclose(
        np.asarray(base[:, :t]), np.asarray(out[:, :t]), atol=1e-6
    )
    assert not np.allclose(np.asarray(base[0, t]), np.asarray(out[0, t]))


def test_pipelined_lm_numerically_equals_dense_lm(devices):
    """lm_pipe's embed/blocks/head are hand-synchronized copies of
    TransformerLM's inline logic (generate.py calls the families
    'equivalent') — pin that mechanically: map a dense lm_tiny param tree
    into the lm_pipe layout and require IDENTICAL logits."""
    dense = create_model("lm_tiny", **KW)
    piped = create_model("lm_pipe", num_stages=1, **KW)
    tokens = _tokens(b=2, s=12, seed=4)
    dp = dense.init(jax.random.PRNGKey(0), tokens)["params"]
    stacked_blocks = jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[dp[f"block{i}"] for i in range(KW["depth"])],
    )
    pipe_params = {
        "embed": {"tok_embed": dp["tok_embed"], "pos_embed": dp["pos_embed"]},
        "blocks": stacked_blocks,
        "head": {"ln_f": dp["ln_f"], "lm_head": dp["lm_head"]},
    }
    want = dense.apply({"params": dp}, tokens)
    got = piped.apply({"params": pipe_params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-6
    )


def test_pipelined_lm_sharded_train_step(devices):
    """dp x pp x tp LM train step: stacked blocks shard over pipe AND
    tensor, loss finite, params update."""
    mesh = build_mesh(MeshConfig(data=2, pipe=2, tensor=2))
    set_current_mesh(mesh)
    try:
        model = create_model("lm_pipe", num_stages=2, num_microbatches=2, **KW)
        cfg = TrainConfig(optimizer="adamw", learning_rate=1e-3)
        tx = make_optimizer(cfg)
        B, S = 8, 17

        def init_fn(r):
            return create_state(
                model, tx, rng=r, sample_input=jnp.zeros((B, S - 1), jnp.int32)
            )

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        rules = param_sharding_rules("lm_pipe")
        shardings = shard_state(abstract, mesh, rules)
        state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
        qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
        shard_shape = qkv.addressable_shards[0].data.shape
        assert shard_shape[0] == qkv.shape[0] // 2  # pipe (depth dim)
        assert shard_shape[3] == qkv.shape[3] // 2  # tensor (heads dim)
        emb = state.params["embed"]["tok_embed"]["embedding"]
        assert emb.addressable_shards[0].data.shape[0] == VOCAB // 2  # vocab/T

        bsh = batch_sharding(mesh)
        step = make_lm_train_step(
            model, tx, mesh=mesh, state_shardings=shardings,
            batch_shardings=bsh,
        )
        batch = {"tokens": _tokens(B, S, seed=3)}
        before = np.asarray(jax.device_get(
            jax.tree.leaves(state.params)[0]))
        state, metrics = _partial_manual(step, state, batch)
        assert np.isfinite(float(metrics["loss"]))
        after = np.asarray(jax.device_get(jax.tree.leaves(state.params)[0]))
        assert not np.allclose(before, after)
    finally:
        set_current_mesh(None)


def test_interleave_tables_valid_and_smaller_bubble():
    """The generated interleaved schedules satisfy every data dependency
    (parallel/interleave.py simulate) and idle fewer device-ticks than
    plain 1F1B (V=1) at the same P and M."""
    from ddp_practice_tpu.parallel.interleave import build_tables, simulate

    for (P_, V, M) in [(2, 2, 4), (4, 2, 8), (2, 3, 4), (4, 3, 8)]:
        tb = build_tables(P_, V, M)
        simulate(tb, P_, V, M)
        flat = build_tables(P_, 1, M)
        simulate(flat, P_, 1, M)
        assert tb.bubble_fraction() < flat.bubble_fraction(), (
            P_, V, M, tb.bubble_fraction(), flat.bubble_fraction()
        )


@pytest.mark.parametrize("microbatches", [4])
def test_interleaved_loss_and_grads_match_sequential(devices, microbatches):
    """Interleaved 1F1B (virtual chunks, schedule tables from
    parallel/interleave.py) computes the SAME mean loss, counts, and
    grads as autodiff of the sequential model — P=2 devices x V=2
    chunks over the 4 blocks."""
    mesh = build_mesh(MeshConfig(data=2, pipe=2))
    set_current_mesh(mesh)
    try:
        piped = create_model("lm_pipe", num_stages=2, schedule="interleaved",
                             num_virtual=2, num_microbatches=microbatches,
                             **KW)
        seq = create_model("lm_pipe", num_stages=1, **KW)
        tokens = _tokens(seed=11)
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        variables = seq.init(jax.random.PRNGKey(2), tokens[:, :-1])

        from ddp_practice_tpu.ops.losses import accuracy_counts, cross_entropy

        def seq_loss(p):
            logits = seq.apply({"params": p}, inputs)
            return cross_entropy(logits, targets), logits

        (want_loss, want_logits), want_grads = jax.value_and_grad(
            seq_loss, has_aux=True
        )(variables["params"])
        want_correct, want_total = accuracy_counts(want_logits, targets)
        (loss, counts), grads = jax.jit(
            lambda p: piped.loss_and_grad(p, inputs, targets)
        )(variables["params"])

        np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
        assert float(counts["correct"]) == float(want_correct)
        assert float(counts["total"]) == float(want_total)
        flat_w, _ = jax.tree_util.tree_flatten_with_path(want_grads)
        flat_g = jax.tree.leaves(grads)
        assert len(flat_w) == len(flat_g)
        for (path, w), g in zip(flat_w, flat_g):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4,
                err_msg=jax.tree_util.keystr(path),
            )
    finally:
        set_current_mesh(None)
