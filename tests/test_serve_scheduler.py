"""Scheduler policy (serve/scheduler.py) under a deterministic fake clock.

The acceptance trace: 20+ requests with mixed prompt lengths and an
early-EOS sequence, replayed on virtual time. Pinned: slot REUSE (a
later request occupies a slot an earlier one freed), zero
recompilation churn (jit cache sizes constant after warmup), bounded-
queue shedding, deadline timeouts (queued and running), impossible-
request rejection, and the epoch reset that rewinds the shared cursor
when the position budget drains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import (
    EngineConfig,
    FakeClock,
    Request,
    Scheduler,
    ServeMetrics,
    SlotEngine,
)

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _greedy_eos(lm, prompt, steps=12):
    """Token the one-shot greedy path emits first — used as the trace's
    EOS id so at least one request genuinely stops early."""
    from ddp_practice_tpu.inference import make_generate_fn

    model, params = lm
    gen = jax.jit(make_generate_fn(model, max_new_tokens=steps,
                                   temperature=0.0))
    out = np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))
    return int(out[0, len(prompt)])


@pytest.mark.slow  # ~18 s: replays the 22-request trace twice
def test_fake_clock_trace_20_requests(devices, lm):
    """The headline trace: deterministic, slot-reusing, compile-stable."""
    model, params = lm
    prompt0 = [3, 1, 4, 1, 5]
    eos = _greedy_eos(lm, prompt0)
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=3, max_len=96, prompt_buckets=(8,), eos_id=eos,
    ))
    metrics = ServeMetrics()
    clock = FakeClock(step_s=0.01)
    sched = Scheduler(engine, clock=clock, max_queue=64, metrics=metrics)

    rng = np.random.default_rng(7)
    n_req = 22
    # request 0 hits EOS on its first decode step (prompt0's greedy
    # continuation IS the eos token); the rest are random mixed lengths
    reqs = [Request(rid=0, prompt=prompt0, max_new_tokens=10)]
    for i in range(1, n_req):
        plen = int(rng.integers(1, 9))
        reqs.append(Request(
            rid=i, prompt=rng.integers(0, VOCAB, plen).tolist(),
            max_new_tokens=int(rng.integers(2, 9)),
        ))

    admitted_slots = {}
    orig_admit = engine.admit

    def tracking_admit(prompt, **kw):
        slot = orig_admit(prompt, **kw)
        admitted_slots.setdefault(slot, []).append(clock.now())
        return slot

    engine.admit = tracking_admit

    # feed two requests per tick — arrival interleaves with decode
    i = 0
    warm_stats = None
    while not (i >= n_req and sched.idle):
        for _ in range(2):
            if i < n_req:
                assert sched.submit(reqs[i])
                i += 1
        sched.step()
        if warm_stats is None and len(sched.completions) >= 3:
            warm_stats = engine.compile_stats()  # after warmup

    comps = {c.rid: c for c in sched.completions}
    assert len(comps) == n_req
    # the early-EOS request stopped at one token (the EOS itself)
    assert comps[0].status == "eos" and len(comps[0].tokens) == 1
    assert comps[0].tokens[0] == eos
    # everyone else ran to their own cap or a genuine EOS
    for c in comps.values():
        assert c.status in ("eos", "length")
        assert c.ttft is not None and c.ttft >= 0
    # slot reuse: 22 requests through 3 slots — some slot served many
    assert max(len(v) for v in admitted_slots.values()) >= 2
    assert sum(len(v) for v in admitted_slots.values()) == n_req
    # no recompilation churn: cache sizes after warmup == at the end
    assert warm_stats == engine.compile_stats()
    assert engine.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
    }
    # replaying the same trace on a fresh engine is bit-identical
    engine2 = SlotEngine(model, params, EngineConfig(
        max_slots=3, max_len=96, prompt_buckets=(8,), eos_id=eos,
    ))
    sched2 = Scheduler(engine2, clock=FakeClock(step_s=0.01), max_queue=64)
    i = 0
    while not (i >= n_req and sched2.idle):
        for _ in range(2):
            if i < n_req:
                sched2.submit(Request(
                    rid=reqs[i].rid, prompt=reqs[i].prompt,
                    max_new_tokens=reqs[i].max_new_tokens,
                ))
                i += 1
        sched2.step()
    comps2 = {c.rid: c for c in sched2.completions}
    for rid in comps:
        assert comps[rid].tokens == comps2[rid].tokens
        assert comps[rid].finish == comps2[rid].finish


def test_queue_bound_sheds(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=96, prompt_buckets=(8,),
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=2)
    results = [
        sched.submit(Request(rid=i, prompt=[1, 2], max_new_tokens=4))
        for i in range(5)
    ]
    assert results == [True, True, False, False, False]
    shed = [c for c in sched.completions if c.status == "shed"]
    assert [c.rid for c in shed] == [2, 3, 4]
    sched.run_until_idle()
    ok = [c for c in sched.completions if c.status == "length"]
    assert sorted(c.rid for c in ok) == [0, 1]


def test_deadlines_queued_and_running(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=96, prompt_buckets=(8,),
    ))
    clock = FakeClock(step_s=0.01)
    sched = Scheduler(engine, clock=clock, max_queue=8)
    # r0 occupies the single slot for a while; r1's deadline expires in
    # the queue; r2 starts but can't finish before its deadline
    sched.submit(Request(rid=0, prompt=[1], max_new_tokens=30))
    sched.submit(Request(rid=1, prompt=[2], max_new_tokens=4,
                         deadline=clock.now() + 0.05))
    sched.submit(Request(rid=2, prompt=[3], max_new_tokens=50,
                         deadline=clock.now() + 0.35))
    sched.run_until_idle()
    by_rid = {c.rid: c for c in sched.completions}
    assert by_rid[0].status == "length" and len(by_rid[0].tokens) == 30
    assert by_rid[1].status == "timeout" and by_rid[1].tokens == []
    assert by_rid[2].status == "timeout" and 0 < len(by_rid[2].tokens) < 50


def test_impossible_requests_rejected(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=24, prompt_buckets=(8,),
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=8)
    sched.submit(Request(rid=0, prompt=list(range(1, 10)),  # > bucket 8
                         max_new_tokens=4))
    sched.submit(Request(rid=1, prompt=[1],
                         max_new_tokens=99))  # > fresh-pool headroom 16
    sched.submit(Request(rid=2, prompt=[1], max_new_tokens=4))
    # zero/negative token budgets reject at the door (needed=0 would
    # bypass every headroom guard downstream)
    assert not sched.submit(Request(rid=3, prompt=[1], max_new_tokens=0))
    sched.run_until_idle()
    by_rid = {c.rid: c for c in sched.completions}
    assert by_rid[0].status == "rejected"
    assert by_rid[1].status == "rejected"
    assert by_rid[2].status == "length"
    assert by_rid[3].status == "rejected"


def test_epoch_reset_keeps_serving(devices, lm):
    """A tiny position budget forces cursor rewinds mid-trace; requests
    keep completing correctly across resets."""
    from ddp_practice_tpu.inference import make_generate_fn

    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=2, max_len=24, prompt_buckets=(8,),  # 16 decode positions
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=16)
    prompts = [[1 + i, 2, 3] for i in range(6)]
    for i, p in enumerate(prompts):
        sched.submit(Request(rid=i, prompt=p, max_new_tokens=10))
    sched.run_until_idle()
    assert len(sched.completions) == 6
    gen = jax.jit(make_generate_fn(model, max_new_tokens=10, temperature=0.0))
    for c in sched.completions:
        assert c.status == "length"
        want = np.asarray(gen(
            params, jnp.asarray([prompts[c.rid]], jnp.int32)
        ))
        assert c.tokens == want[0, len(prompts[c.rid]):].tolist()
    # churn through 6 requests across resets: still just two programs
    assert engine.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
    }


# --------------------------------------------------- preemption policy
# Host-pure: the staging/requeue logic runs entirely scheduler-side, so
# a stub engine that always gates "later" exercises it without a
# compile. The engine-side preemption mechanics (blocks actually
# freeing, token identity across evict/readmit) are pinned with real
# engines in tests/test_kv_pages.py and test_serve_equivalence.py.

class _BlockedEngine:
    """Minimal PagedEngine protocol surface for the admit loop: every
    gate says "later", every fair victim can be preempted."""

    class config:
        decode_burst = 1

    num_free = 1

    def __init__(self, feasible=True):
        self.feasible = feasible
        self.preempts = []

    def admit_gate(self, prompt_len, needed, prompt=None):
        return "later"

    def make_room(self, *a, **k):
        return False

    def preempt_headroom(self, slots, prompt_len, prompt=None):
        return self.feasible and len(slots) > 0

    def preempt(self, slot):
        self.preempts.append(slot)

    def take_preempted(self):
        return []


def _blocked_sched(feasible=True):
    from ddp_practice_tpu.serve.scheduler import _Running

    eng = _BlockedEngine(feasible)
    sched = Scheduler(eng, clock=FakeClock())
    sched.queue.append(
        Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4, arrival=0.0))
    # two running victims, both strictly younger by arrival; slot 7
    # (seq 11) is the youngest-ADMITTED and must be evicted first
    for slot, (rid, arr, seq) in {5: (1, 1.0, 10), 7: (2, 2.0, 11)}.items():
        sched.running[slot] = _Running(
            req=Request(rid=rid, prompt=[rid, rid], max_new_tokens=4,
                        arrival=arr),
            slot=slot, seq=seq)
    return eng, sched


def test_preempted_victims_requeue_in_arrival_order(devices):
    """Multi-victim preemption requeues victims behind the blocked head
    in ARRIVAL order — the older victim readmits first, so it can never
    turn around and (fairly) re-preempt the younger one it now leads."""
    eng, sched = _blocked_sched()
    sched._admit()
    assert eng.preempts == [7, 5]          # youngest-admitted evicts first
    assert not sched.running
    assert [r.rid for r in sched.queue] == [0, 1, 2]   # arrival order


def test_no_preemption_when_it_cannot_admit_the_head(devices):
    """Feasibility gate: when even evicting EVERY fair victim cannot
    surface enough blocks, nobody is preempted — the victims keep their
    decode progress and the head waits for releases."""
    eng, sched = _blocked_sched(feasible=False)
    sched._admit()
    assert eng.preempts == []
    assert sorted(sched.running) == [5, 7]             # untouched
    assert [r.rid for r in sched.queue] == [0]


def test_unfair_high_seq_runner_does_not_shield_fair_victims(devices):
    """A readmitted continuation (fresh high admission seq, ORIGINAL old
    arrival) is skipped, not a reason to bail: the youngest FAIR victim
    behind it is still evicted for an older blocked head."""
    from ddp_practice_tpu.serve.scheduler import _Running

    eng = _BlockedEngine()
    sched = Scheduler(eng, clock=FakeClock())
    sched.queue.append(
        Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4, arrival=2.0))
    # slot 5: continuation of an OLD request (arrival 1.0) readmitted
    # after a preemption — highest seq, but unfair for this head
    sched.running[5] = _Running(
        req=Request(rid=1, prompt=[1, 1], max_new_tokens=4, arrival=1.0),
        slot=5, seq=9)
    sched.running[7] = _Running(
        req=Request(rid=2, prompt=[2, 2], max_new_tokens=4, arrival=3.0),
        slot=7, seq=4)
    sched._admit()
    assert eng.preempts == [7]             # the fair victim, despite seq 4
    assert sorted(sched.running) == [5]    # the old continuation survives
    assert [r.rid for r in sched.queue] == [0, 2]


def test_stale_continuation_falls_back_to_original_prompt(devices):
    """A continuation whose warm prefix aged out of the cache while it
    queued (prompt+prefix no longer fits a bucket -> gate "never") is
    retried from the ORIGINAL prompt instead of being rejected."""

    class _Eng(_BlockedEngine):
        def admit_gate(self, prompt_len, needed, prompt=None):
            return "never" if prompt_len > 4 else "later"

    eng = _Eng()
    sched = Scheduler(eng, clock=FakeClock())
    orig = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=6, arrival=0.0,
                   trace_id="t0")
    sched._resume[0] = {"orig": orig, "prefix": [9, 9], "ftt": 0.5}
    sched.queue.append(Request(          # the stale continuation
        rid=0, prompt=[1, 2, 3, 9, 9], max_new_tokens=4, arrival=0.0,
        trace_id="t0"))
    sched._admit()
    assert sched.completions == []       # NOT rejected
    assert len(sched.queue) == 1
    retry = sched.queue[0]
    assert list(retry.prompt) == [1, 2, 3]         # original prompt
    assert retry.max_new_tokens == 6               # full budget restored
    assert retry.trace_id == "t0" and retry.arrival == 0.0
    assert retry.submitted is not None   # prior attempt not booked as queue_s
    assert 0 not in sched._resume        # prefix dropped: regenerated


def test_continuation_victims_requeue_by_arrival_not_seq(devices):
    """A readmitted continuation carries a fresh HIGH admission seq but
    its ORIGINAL arrival — staged eviction order (descending seq) must
    not leak into the queue, or the younger victim readmits first and
    gets fairly re-preempted by the older one: churn the sort by
    arrival prevents."""
    from ddp_practice_tpu.serve.scheduler import _Running

    eng = _BlockedEngine()
    sched = Scheduler(eng, clock=FakeClock())
    sched.queue.append(
        Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4, arrival=0.5))
    # slot 5: continuation (arrival 1.0, readmitted -> seq 100); slot 7:
    # plain younger runner (arrival 2.0, seq 50). Both fair for the head.
    sched.running[5] = _Running(
        req=Request(rid=1, prompt=[1, 1], max_new_tokens=4, arrival=1.0),
        slot=5, seq=100)
    sched.running[7] = _Running(
        req=Request(rid=2, prompt=[2, 2], max_new_tokens=4, arrival=2.0),
        slot=7, seq=50)
    sched._admit()
    assert eng.preempts == [5, 7]          # evicted in seq order (LIFO)
    assert [r.rid for r in sched.queue] == [0, 1, 2]   # ARRIVAL order


# ------------------------------------------------------ token streaming
def test_stream_chunks_match_completions(devices, lm):
    """TokenChunk emission (the streaming side channel): per rid the
    chunks' concatenated tokens ARE the completion's tokens, offsets
    and seq are contiguous, and exactly one final chunk carries the
    terminal status — chunk delivery is complete exactly when the
    completion exists. stream=False (the control arm) builds none."""
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=2, max_len=96, prompt_buckets=(8,),
    ))
    sched = Scheduler(engine, clock=FakeClock(step_s=0.01), max_queue=8)
    reqs = [Request(rid=i, prompt=[1 + i, 2, 3], max_new_tokens=4 + i)
            for i in range(4)]
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    by_rid = {c.rid: c for c in sched.completions}
    assert len(by_rid) == 4

    per_rid = {}
    for ch in sched.chunks:
        per_rid.setdefault(ch.rid, []).append(ch)
    assert set(per_rid) == set(by_rid)
    for rid, chunks in per_rid.items():
        c = by_rid[rid]
        assert [ch.seq for ch in chunks] == list(range(len(chunks)))
        toks, offset = [], 0
        for ch in chunks:
            assert ch.start == offset        # offset-contiguous
            toks.extend(ch.tokens)
            offset += len(ch.tokens)
            assert ch.trace_id == c.trace_id
        assert toks == c.tokens
        finals = [ch for ch in chunks if ch.final]
        assert len(finals) == 1 and finals[0] is chunks[-1]
        assert finals[0].status == c.status
    # seq counters retire with their rid: live state stays O(in-flight)
    assert sched._chunk_seq == {}

    # control arm: stream=False emits nothing (end-of-request delivery)
    sched2 = Scheduler(engine, clock=FakeClock(step_s=0.01),
                       max_queue=8, stream=False)
    sched2.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    sched2.run_until_idle()
    assert sched2.chunks == []
    assert sched2.completions[0].tokens == by_rid[0].tokens
