"""Scheduler policy (serve/scheduler.py) under a deterministic fake clock.

The acceptance trace: 20+ requests with mixed prompt lengths and an
early-EOS sequence, replayed on virtual time. Pinned: slot REUSE (a
later request occupies a slot an earlier one freed), zero
recompilation churn (jit cache sizes constant after warmup), bounded-
queue shedding, deadline timeouts (queued and running), impossible-
request rejection, and the epoch reset that rewinds the shared cursor
when the position budget drains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import (
    EngineConfig,
    FakeClock,
    Request,
    Scheduler,
    ServeMetrics,
    SlotEngine,
)

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _greedy_eos(lm, prompt, steps=12):
    """Token the one-shot greedy path emits first — used as the trace's
    EOS id so at least one request genuinely stops early."""
    from ddp_practice_tpu.inference import make_generate_fn

    model, params = lm
    gen = jax.jit(make_generate_fn(model, max_new_tokens=steps,
                                   temperature=0.0))
    out = np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))
    return int(out[0, len(prompt)])


@pytest.mark.slow  # ~18 s: replays the 22-request trace twice
def test_fake_clock_trace_20_requests(devices, lm):
    """The headline trace: deterministic, slot-reusing, compile-stable."""
    model, params = lm
    prompt0 = [3, 1, 4, 1, 5]
    eos = _greedy_eos(lm, prompt0)
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=3, max_len=96, prompt_buckets=(8,), eos_id=eos,
    ))
    metrics = ServeMetrics()
    clock = FakeClock(step_s=0.01)
    sched = Scheduler(engine, clock=clock, max_queue=64, metrics=metrics)

    rng = np.random.default_rng(7)
    n_req = 22
    # request 0 hits EOS on its first decode step (prompt0's greedy
    # continuation IS the eos token); the rest are random mixed lengths
    reqs = [Request(rid=0, prompt=prompt0, max_new_tokens=10)]
    for i in range(1, n_req):
        plen = int(rng.integers(1, 9))
        reqs.append(Request(
            rid=i, prompt=rng.integers(0, VOCAB, plen).tolist(),
            max_new_tokens=int(rng.integers(2, 9)),
        ))

    admitted_slots = {}
    orig_admit = engine.admit

    def tracking_admit(prompt, **kw):
        slot = orig_admit(prompt, **kw)
        admitted_slots.setdefault(slot, []).append(clock.now())
        return slot

    engine.admit = tracking_admit

    # feed two requests per tick — arrival interleaves with decode
    i = 0
    warm_stats = None
    while not (i >= n_req and sched.idle):
        for _ in range(2):
            if i < n_req:
                assert sched.submit(reqs[i])
                i += 1
        sched.step()
        if warm_stats is None and len(sched.completions) >= 3:
            warm_stats = engine.compile_stats()  # after warmup

    comps = {c.rid: c for c in sched.completions}
    assert len(comps) == n_req
    # the early-EOS request stopped at one token (the EOS itself)
    assert comps[0].status == "eos" and len(comps[0].tokens) == 1
    assert comps[0].tokens[0] == eos
    # everyone else ran to their own cap or a genuine EOS
    for c in comps.values():
        assert c.status in ("eos", "length")
        assert c.ttft is not None and c.ttft >= 0
    # slot reuse: 22 requests through 3 slots — some slot served many
    assert max(len(v) for v in admitted_slots.values()) >= 2
    assert sum(len(v) for v in admitted_slots.values()) == n_req
    # no recompilation churn: cache sizes after warmup == at the end
    assert warm_stats == engine.compile_stats()
    assert engine.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
    }
    # replaying the same trace on a fresh engine is bit-identical
    engine2 = SlotEngine(model, params, EngineConfig(
        max_slots=3, max_len=96, prompt_buckets=(8,), eos_id=eos,
    ))
    sched2 = Scheduler(engine2, clock=FakeClock(step_s=0.01), max_queue=64)
    i = 0
    while not (i >= n_req and sched2.idle):
        for _ in range(2):
            if i < n_req:
                sched2.submit(Request(
                    rid=reqs[i].rid, prompt=reqs[i].prompt,
                    max_new_tokens=reqs[i].max_new_tokens,
                ))
                i += 1
        sched2.step()
    comps2 = {c.rid: c for c in sched2.completions}
    for rid in comps:
        assert comps[rid].tokens == comps2[rid].tokens
        assert comps[rid].finish == comps2[rid].finish


def test_queue_bound_sheds(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=96, prompt_buckets=(8,),
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=2)
    results = [
        sched.submit(Request(rid=i, prompt=[1, 2], max_new_tokens=4))
        for i in range(5)
    ]
    assert results == [True, True, False, False, False]
    shed = [c for c in sched.completions if c.status == "shed"]
    assert [c.rid for c in shed] == [2, 3, 4]
    sched.run_until_idle()
    ok = [c for c in sched.completions if c.status == "length"]
    assert sorted(c.rid for c in ok) == [0, 1]


def test_deadlines_queued_and_running(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=96, prompt_buckets=(8,),
    ))
    clock = FakeClock(step_s=0.01)
    sched = Scheduler(engine, clock=clock, max_queue=8)
    # r0 occupies the single slot for a while; r1's deadline expires in
    # the queue; r2 starts but can't finish before its deadline
    sched.submit(Request(rid=0, prompt=[1], max_new_tokens=30))
    sched.submit(Request(rid=1, prompt=[2], max_new_tokens=4,
                         deadline=clock.now() + 0.05))
    sched.submit(Request(rid=2, prompt=[3], max_new_tokens=50,
                         deadline=clock.now() + 0.35))
    sched.run_until_idle()
    by_rid = {c.rid: c for c in sched.completions}
    assert by_rid[0].status == "length" and len(by_rid[0].tokens) == 30
    assert by_rid[1].status == "timeout" and by_rid[1].tokens == []
    assert by_rid[2].status == "timeout" and 0 < len(by_rid[2].tokens) < 50


def test_impossible_requests_rejected(devices, lm):
    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=24, prompt_buckets=(8,),
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=8)
    sched.submit(Request(rid=0, prompt=list(range(1, 10)),  # > bucket 8
                         max_new_tokens=4))
    sched.submit(Request(rid=1, prompt=[1],
                         max_new_tokens=99))  # > fresh-pool headroom 16
    sched.submit(Request(rid=2, prompt=[1], max_new_tokens=4))
    # zero/negative token budgets reject at the door (needed=0 would
    # bypass every headroom guard downstream)
    assert not sched.submit(Request(rid=3, prompt=[1], max_new_tokens=0))
    sched.run_until_idle()
    by_rid = {c.rid: c for c in sched.completions}
    assert by_rid[0].status == "rejected"
    assert by_rid[1].status == "rejected"
    assert by_rid[2].status == "length"
    assert by_rid[3].status == "rejected"


def test_epoch_reset_keeps_serving(devices, lm):
    """A tiny position budget forces cursor rewinds mid-trace; requests
    keep completing correctly across resets."""
    from ddp_practice_tpu.inference import make_generate_fn

    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=2, max_len=24, prompt_buckets=(8,),  # 16 decode positions
    ))
    sched = Scheduler(engine, clock=FakeClock(), max_queue=16)
    prompts = [[1 + i, 2, 3] for i in range(6)]
    for i, p in enumerate(prompts):
        sched.submit(Request(rid=i, prompt=p, max_new_tokens=10))
    sched.run_until_idle()
    assert len(sched.completions) == 6
    gen = jax.jit(make_generate_fn(model, max_new_tokens=10, temperature=0.0))
    for c in sched.completions:
        assert c.status == "length"
        want = np.asarray(gen(
            params, jnp.asarray([prompts[c.rid]], jnp.int32)
        ))
        assert c.tokens == want[0, len(prompts[c.rid]):].tolist()
    # churn through 6 requests across resets: still just two programs
    assert engine.compile_stats() == {
        "prefill_compiles": 1, "decode_compiles": 1,
    }
