"""Rotary position embeddings (ops/rope.py) and their composition with the
LM family, KV-cache decode, and sequence parallelism.

Nothing to cite in the reference (no sequence axis; SURVEY §5.7). Pinned:
the defining relative-position property (scores depend only on i - j),
causality of the rope LM, cached decode == full forward (the cursor offset
is the part a naive port gets wrong), and the seq-sharded rope decoder
matching the dense one (rotation happens before the SP island, so ring
K/V blocks travel pre-rotated).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.inference import make_cache, make_generate_fn
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.ops.rope import apply_rope
from ddp_practice_tpu.parallel.mesh import build_mesh
from ddp_practice_tpu.parallel.ring import set_current_mesh

VOCAB = 32


def _rope_lm(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("max_len", 64)
    kw.setdefault("hidden_dim", 64)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 128)
    kw.setdefault("pos_emb", "rope")
    return create_model("lm_tiny", **kw)


@pytest.mark.fast
def test_rope_scores_are_relative(devices):
    """q_i · k_j after rotation depends only on i - j: shifting both
    positions by the same amount leaves the dot product unchanged."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)

    def score(i, j):
        qi = apply_rope(q, jnp.asarray([i]))
        kj = apply_rope(k, jnp.asarray([j]))
        return float(jnp.sum(qi * kj))

    np.testing.assert_allclose(score(3, 1), score(10, 8), rtol=1e-5)
    np.testing.assert_allclose(score(0, 0), score(7, 7), rtol=1e-5)
    # and it DOES vary with the offset (not a no-op)
    assert abs(score(3, 1) - score(3, 2)) > 1e-6


def test_rope_rejects_odd_head_dim(devices):
    with pytest.raises(ValueError, match="even"):
        apply_rope(jnp.zeros((1, 2, 1, 5)), jnp.arange(2))


def test_rope_lm_has_no_position_table_and_is_causal(devices):
    model = _rope_lm()
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, VOCAB, (1, 16)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), tokens)
    assert "pos_embed" not in variables["params"]
    base = model.apply(variables, tokens)
    t = 9
    perturbed = tokens.at[0, t].set((int(tokens[0, t]) + 5) % VOCAB)
    out = model.apply(variables, perturbed)
    np.testing.assert_array_equal(np.asarray(base[:, :t]), np.asarray(out[:, :t]))
    assert not np.allclose(np.asarray(base[:, t]), np.asarray(out[:, t]))
    # position is not ignored either: swapping two prompt tokens changes
    # downstream logits
    swapped = tokens.at[0, 2].set(int(tokens[0, 3])).at[0, 3].set(int(tokens[0, 2]))
    assert not np.allclose(np.asarray(base[:, -1]), np.asarray(model.apply(variables, swapped)[:, -1]))


def test_rope_cached_decode_matches_full_forward(devices):
    """The decode path rotates the incoming block at its ABSOLUTE positions
    (cursor offset) — prefill + steps must equal the full forward."""
    model = _rope_lm()
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)

    prompt_len, total = 5, 12
    cache = make_cache(model, 2, total)
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        tokens[:, :prompt_len], decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :prompt_len]),
        rtol=2e-5, atol=2e-5,
    )
    cache = mut["cache"]
    for t in range(prompt_len, total):
        step_logits, mut = model.apply(
            {"params": params, "cache": cache},
            tokens[:, t:t + 1], decode=True, mutable=["cache"],
        )
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full[:, t]),
            rtol=2e-5, atol=2e-5,
        )


@pytest.mark.slow    # 10.9s measured — over the tier-1 10s line
def test_rope_greedy_generate_matches_naive(devices):
    model = _rope_lm()
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    n_new = 8
    fast = np.asarray(
        jax.jit(make_generate_fn(model, max_new_tokens=n_new, temperature=0.0))(
            params, prompt
        )
    )
    seq = prompt
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(fast, np.asarray(seq))


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_rope_lm_sequence_parallel_matches_dense(devices, sp_impl):
    """Rotation is applied before the SP shard_map island, so the sharded
    rope decoder must reproduce the dense one bit-for-float."""
    mesh = build_mesh(MeshConfig(data=1, seq=8))
    set_current_mesh(mesh)
    try:
        dense = _rope_lm(num_heads=8)
        sharded = _rope_lm(
            num_heads=8, seq_axis=MeshConfig.AXIS_SEQ, sp_impl=sp_impl
        )
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, VOCAB, (2, 32)), jnp.int32
        )
        variables = dense.init(jax.random.PRNGKey(0), tokens)
        base = dense.apply(variables, tokens)
        sp = sharded.apply(variables, tokens)
        np.testing.assert_allclose(
            np.asarray(sp), np.asarray(base), rtol=2e-4, atol=2e-4
        )
    finally:
        set_current_mesh(None)
