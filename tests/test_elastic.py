"""Failure-detection / elastic-recovery tests (SURVEY §5.2-5.3).

The reference has no retry, health checks, or sync assertions; these pin
the behaviors the new framework adds: watchdog fires on stall and not on
progress, restart driver resumes from checkpoints, sync check is a no-op
single-process, and end-to-end fit() survives an injected mid-training
failure by restoring its checkpoint.
"""

import time

import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.train.elastic import (
    StepWatchdog,
    assert_in_sync,
    run_with_restarts,
)


def test_watchdog_fires_on_stall():
    fired = []
    wd = StepWatchdog(0.2, on_timeout=lambda s: fired.append(s)).start()
    wd.beat()  # steady state reached; grace window over
    time.sleep(0.6)
    wd.stop()
    assert fired and fired[0] >= 0.2


def test_watchdog_grace_before_first_beat():
    """Compile time (pre-first-beat) gets timeout * first_beat_grace."""
    fired = []
    wd = StepWatchdog(
        0.1, on_timeout=lambda s: fired.append(s), first_beat_grace=10
    ).start()
    time.sleep(0.5)  # > timeout, < timeout * grace
    wd.stop()
    assert not fired


@pytest.mark.fast
def test_watchdog_quiet_with_beats():
    fired = []
    wd = StepWatchdog(0.4, on_timeout=lambda s: fired.append(s)).start()
    for _ in range(6):
        time.sleep(0.1)
        wd.beat()
    wd.stop()
    assert not fired


def test_watchdog_probe_detects_hung_device():
    """A hung step must trip the watchdog even though the host can keep
    dispatching (VERDICT weak #3): probe() beats only after the fetch
    resolves, so a fetch that never returns ends the beats."""
    fired = []
    wd = StepWatchdog(0.2, on_timeout=lambda s: fired.append(s)).start()
    wd.beat()

    def hung_fetch(_):
        time.sleep(1.0)  # a collective that never completes

    wd.probe(object(), fetch=hung_fetch)  # blocks; no beat until done
    wd.stop()
    assert fired, "watchdog did not fire while the probe was hung"


def test_watchdog_probe_beats_on_resolution():
    fired = []
    wd = StepWatchdog(0.3, on_timeout=lambda s: fired.append(s)).start()
    for _ in range(4):
        time.sleep(0.1)
        wd.probe(np.float32(1.0), fetch=lambda v: v)  # instant resolve
    wd.stop()
    assert not fired


@pytest.mark.fast
def test_assert_in_sync_single_process_noop():
    assert_in_sync(12345)  # 1 process: trivially in sync


def test_run_with_restarts_retries_then_succeeds():
    calls = []

    class FlakyTrainer:
        def __init__(self, resume):
            self.resume = resume

        def fit(self):
            calls.append(self.resume)
            if len(calls) < 3:
                raise RuntimeError("injected failure")
            return {"ok": True, "resumed": self.resume}

    out = run_with_restarts(FlakyTrainer, max_restarts=2)
    assert out["ok"] and out["resumed"] is True
    assert calls == [False, True, True]  # first cold, retries resume


def test_run_with_restarts_backoff_and_counter():
    """Restart delays follow the shared deterministic backoff schedule
    (utils/backoff.py) and each restart bumps train_restarts_total."""
    from ddp_practice_tpu.utils.backoff import backoff_delay
    from ddp_practice_tpu.utils.metrics import MetricsRegistry

    calls, slept = [], []
    registry = MetricsRegistry()

    class Flaky:
        def __init__(self, resume):
            pass

        def fit(self):
            calls.append(1)
            if len(calls) < 4:
                raise RuntimeError("injected")
            return {"ok": True}

    out = run_with_restarts(
        Flaky, max_restarts=3, restart_delay_s=0.1, jitter=0.5, seed=5,
        metrics=registry, sleep=slept.append,
    )
    assert out["ok"] and len(calls) == 4
    want = [
        backoff_delay(i, base_s=0.1, factor=2.0, max_s=300.0,
                      jitter=0.5, seed=5)
        for i in range(3)
    ]
    assert slept == want          # deterministic schedule, replayable
    assert want[0] < want[1] < want[2]  # and actually growing
    assert registry.counter("train_restarts_total").value == 3


def test_run_with_restarts_zero_delay_never_sleeps():
    """restart_delay_s=0 keeps the legacy immediate-restart path."""
    calls, slept = [], []

    class FailOnce:
        def __init__(self, resume):
            pass

        def fit(self):
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("injected")
            return {"ok": True}

    out = run_with_restarts(FailOnce, max_restarts=1, sleep=slept.append)
    assert out["ok"] and slept == []


def test_run_with_restarts_exhausts():
    class AlwaysFails:
        def __init__(self, resume):
            pass

        def fit(self):
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_with_restarts(AlwaysFails, max_restarts=1)


@pytest.mark.slow
def test_fit_recovers_from_injected_failure(tmp_path):
    """End-to-end: a train step that dies mid-run on the first attempt;
    the elastic driver restores the per-epoch checkpoint and finishes.

    QUARANTINED in a subprocess (tests/elastic_worker.py): this fit
    segfaults flakily on this image's XLA CPU — crash inside
    block_until_ready, load/memory dependent, reproduces on the
    untouched seed tree — and an in-process SIGSEGV would kill the
    whole pytest session. Signal death gets ONE subprocess rerun (the
    flake is load-dependent, so a retry usually lands) before the
    known-flake skip; each attempt is a fresh tmp subdir so a partial
    checkpoint from the crashed run can't corrupt the retry. Real
    assertion failures still fail here immediately (nonzero exit,
    traceback in the captured output) — only signal death reruns."""
    import os
    import signal
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")
    repo_root = os.path.dirname(os.path.dirname(worker))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, worker, str(tmp_path / f"try{attempt}")],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=repo_root,
        )
        if proc.returncode >= 0:
            break
    if proc.returncode < 0:
        sig = signal.Signals(-proc.returncode).name
        pytest.skip(
            f"known flaky XLA-CPU crash ({sig}) in the elastic e2e fit "
            f"twice in a row — pre-existing on the seed tree, see "
            f"tests/elastic_worker.py"
        )
    assert proc.returncode == 0, (
        f"elastic worker failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}"
    )
    assert "ALL_OK" in proc.stdout.splitlines()[-1]
