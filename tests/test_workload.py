"""Workload lab (serve/workload.py): spec validation, deterministic
plan expansion, the add-a-tenant prefix-stability contract, arrival
shaping (bursty/diurnal via Lewis thinning), multi-turn session prompt
growth, and the JSON round-trip the bench/CLI seam rides. All host
math — no engines, no clocks."""

import json
import math

import pytest

from ddp_practice_tpu.serve.workload import TenantSpec, WorkloadPlan

VOCAB = 32


def _plan(*tenants, duration_s=20.0):
    return WorkloadPlan(list(tenants), duration_s=duration_s)


# ------------------------------------------------------------ validation
def test_tenant_spec_validates_each_knob():
    with pytest.raises(ValueError):
        TenantSpec(name="")
    with pytest.raises(ValueError):
        TenantSpec(name="t", rate_rps=0.0)
    with pytest.raises(ValueError):
        TenantSpec(name="t", arrivals="lumpy")
    with pytest.raises(ValueError):  # burst window longer than period
        TenantSpec(name="t", arrivals="bursty", burst_every_s=1.0,
                   burst_len_s=2.0)
    with pytest.raises(ValueError):  # a burst must not SLOW the tenant
        TenantSpec(name="t", arrivals="bursty", burst_mult=0.5)
    with pytest.raises(ValueError):  # depth 1 would cross zero rate
        TenantSpec(name="t", arrivals="diurnal", diurnal_depth=1.0)
    with pytest.raises(ValueError):
        TenantSpec(name="t", prompt_len_cap=0)
    with pytest.raises(ValueError):
        TenantSpec(name="t", max_new_sigma=-0.1)
    with pytest.raises(ValueError):
        TenantSpec(name="t", sessions=2, turns_per_session=0)
    with pytest.raises(ValueError):
        TenantSpec(name="t", sessions=2, session_prefix_len=0)


def test_plan_validates_shape():
    with pytest.raises(ValueError):
        WorkloadPlan([])
    with pytest.raises(ValueError):
        WorkloadPlan([TenantSpec(name="a"), TenantSpec(name="a")])
    with pytest.raises(ValueError):
        WorkloadPlan([TenantSpec(name="a")], duration_s=0.0)
    with pytest.raises(ValueError):
        _plan(TenantSpec(name="a")).build(vocab=1)


# ----------------------------------------------------------- determinism
def test_build_is_deterministic_and_arrival_sorted():
    plan = _plan(TenantSpec(name="acme", rate_rps=3.0),
                 TenantSpec(name="bulk", rate_rps=8.0, priority=2,
                            hostile=True))
    a = plan.build(vocab=VOCAB, seed=7)
    b = plan.build(vocab=VOCAB, seed=7)
    assert a == b
    assert len(a) > 50
    # rid order == arrival order (what replay harnesses assume)
    assert [r["rid"] for r in a] == list(range(len(a)))
    assert all(x["arrival"] <= y["arrival"] for x, y in zip(a, a[1:]))
    # every row is replayable as-is and attributed
    for r in a:
        assert set(r) == {"rid", "arrival", "prompt", "max_new_tokens",
                          "tenant", "priority"}
        assert 0.0 <= r["arrival"] < plan.duration_s
        assert 1 <= len(r["prompt"]) <= 96
        assert 1 <= r["max_new_tokens"] <= 48
        assert all(0 <= t < VOCAB for t in r["prompt"])
    assert {r["tenant"] for r in a} == {"acme", "bulk"}
    assert all(r["priority"] == 2 for r in a if r["tenant"] == "bulk")
    # a different seed is a different draw
    assert plan.build(vocab=VOCAB, seed=8) != a


def test_adding_a_tenant_never_perturbs_existing_traffic():
    """Child generators spawn off the plan seed by tenant INDEX, so
    extending a plan leaves the original tenants' rows byte-stable —
    the property that makes A/B runs of grown plans comparable."""
    base = _plan(TenantSpec(name="acme", rate_rps=5.0))
    grown = _plan(TenantSpec(name="acme", rate_rps=5.0),
                  TenantSpec(name="new", rate_rps=5.0))

    def _rows(plan, tenant):
        return [
            {k: v for k, v in r.items() if k != "rid"}
            for r in plan.build(vocab=VOCAB, seed=3)
            if r["tenant"] == tenant
        ]

    assert _rows(base, "acme") == _rows(grown, "acme")


# ------------------------------------------------------ arrival shaping
def test_bursty_rates_and_arrival_concentration():
    spec = TenantSpec(name="t", rate_rps=2.0, arrivals="bursty",
                      burst_every_s=10.0, burst_len_s=1.0,
                      burst_mult=8.0)
    assert spec.peak_rate() == 16.0
    assert spec.rate_at(0.5) == 16.0      # inside the window
    assert spec.rate_at(5.0) == 2.0       # between windows
    rows = _plan(spec, duration_s=100.0).build(vocab=VOCAB, seed=0)
    in_burst = [r for r in rows if (r["arrival"] % 10.0) < 1.0]
    # 10% of the clock carries the 8x windows: expect roughly
    # 8/(8+9) ~ 47% of arrivals in-burst; far above the 10% a
    # homogeneous stream would put there
    assert len(in_burst) / len(rows) > 0.3


def test_diurnal_rates_follow_the_sinusoid():
    spec = TenantSpec(name="t", rate_rps=4.0, arrivals="diurnal",
                      diurnal_period_s=60.0, diurnal_depth=0.8)
    assert spec.peak_rate() == pytest.approx(4.0 * 1.8)
    assert spec.rate_at(15.0) == pytest.approx(4.0 * 1.8)   # crest
    assert spec.rate_at(45.0) == pytest.approx(4.0 * 0.2)   # trough
    assert spec.rate_at(0.0) == pytest.approx(4.0)
    rows = _plan(spec, duration_s=120.0).build(vocab=VOCAB, seed=1)
    crest = sum(1 for r in rows
                if math.sin(2 * math.pi * r["arrival"] / 60.0) > 0)
    assert crest / len(rows) > 0.6   # most arrivals ride the crest


def test_heavy_tailed_lengths_are_capped_and_spread():
    spec = TenantSpec(name="t", rate_rps=20.0, prompt_len_mean=8.0,
                      prompt_len_sigma=1.0, prompt_len_cap=32)
    rows = _plan(spec, duration_s=20.0).build(vocab=VOCAB, seed=2)
    lens = [len(r["prompt"]) for r in rows]
    assert max(lens) <= 32 and min(lens) >= 1
    assert len(set(lens)) > 5            # a distribution, not a constant
    # sigma 0 degenerates to the constant median
    flat = TenantSpec(name="t", rate_rps=20.0, prompt_len_mean=8.0,
                      prompt_len_sigma=0.0)
    rows = _plan(flat, duration_s=5.0).build(vocab=VOCAB, seed=2)
    assert {len(r["prompt"]) for r in rows} == {8}


# ------------------------------------------------------------- sessions
def test_session_turns_refeed_the_whole_conversation():
    spec = TenantSpec(name="chat", rate_rps=6.0, sessions=2,
                      turns_per_session=3, session_prefix_len=10)
    rows = _plan(spec, duration_s=10.0).build(vocab=VOCAB, seed=4)
    by_arrival = sorted(rows, key=lambda r: r["arrival"])
    # arrivals round-robin the sessions: chains[s] is session s's turns
    chains = [by_arrival[s::2] for s in range(2)]
    for chain in chains:
        for prev, cur in zip(chain, chain[1:3]):
            # turn N's prompt extends turn N-1's whole prompt — the
            # re-fed history the radix prefix cache exists for
            assert cur["prompt"][:len(prev["prompt"])] == prev["prompt"]
            assert len(cur["prompt"]) > len(prev["prompt"])
        # turn 4 starts a NEW chat on the same shared prefix
        if len(chain) > 3:
            assert chain[3]["prompt"][:10] == chain[0]["prompt"][:10]
            assert len(chain[3]["prompt"]) < len(chain[2]["prompt"])
    # the two sessions have distinct prefixes
    assert chains[0][0]["prompt"][:10] != chains[1][0]["prompt"][:10]


# ------------------------------------------------------------ json seam
def test_plan_json_roundtrip_and_hostile_marking():
    plan = _plan(
        TenantSpec(name="acme", rate_rps=3.0),
        TenantSpec(name="bulk", rate_rps=50.0, hostile=True,
                   arrivals="bursty"),
        duration_s=12.0)
    back = WorkloadPlan.from_json(plan.to_json())
    assert back.duration_s == 12.0
    assert back.tenants == plan.tenants
    assert back.hostile_tenants() == ["bulk"]
    assert back.build(vocab=VOCAB, seed=5) \
        == plan.build(vocab=VOCAB, seed=5)
    # a bare list of tenant objects is a plan with default duration
    bare = WorkloadPlan.from_json(json.dumps([{"name": "solo"}]))
    assert bare.duration_s == 10.0 and bare.tenants[0].name == "solo"


def test_plan_from_json_path_and_error_shapes(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text(_plan(TenantSpec(name="a")).to_json())
    assert WorkloadPlan.from_json(str(p)).tenants[0].name == "a"
    # a mistyped path fails as a missing FILE, not a JSON decode error
    with pytest.raises(FileNotFoundError):
        WorkloadPlan.from_json("no/such/plan.json")
    with pytest.raises(TypeError):  # unknown keys are typos, not config
        WorkloadPlan.from_json('[{"name": "a", "rps": 3}]')
