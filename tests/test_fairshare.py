"""Tenant QoS plane units (serve/fairshare.py + the seams it drives):
Jain's index math, VTC floor-lift/weights/enforcement queries, the
scheduler's weighted-fair head rotation (and its byte-identical-FIFO
off switch), the admission door's typed "fairness" refusal, per-tenant
cost metering + fleet federation, the per-tenant SLO registry's
isolation/overflow semantics, and the tenant-scoped brown-out shed
seam (in-process predicate + remote name-list wire form).

Everything here is host-pure — fake engines, fake completions, fake
RPC clients; no jax compile. The live end-to-end story (fair vs FIFO
under a hostile flood, SIGKILL mid-flood) is pinned by the qos bench
arm + tools/check_qos.py over its checked-in artifacts
(tests/test_tools_artifacts.py)."""

import json
import re
import urllib.request

import pytest

from ddp_practice_tpu.serve import FakeClock, Request, Scheduler
from ddp_practice_tpu.serve.admission import (
    AdmissionController,
    TenantPolicy,
)
from ddp_practice_tpu.serve.fairshare import (
    DEFAULT_TENANT,
    TenantLedger,
    VirtualTokenCounter,
    federate_tenant_reports,
    jains_index,
    tenant_name,
)
from ddp_practice_tpu.serve.slo import SLOConfig, TenantSLORegistry
from ddp_practice_tpu.utils.metrics import (
    MetricsRegistry,
    percentile_summary,
    reset_label_guard,
    set_label_limit,
)


class _C:
    """Completion stand-in: just the attributes TenantLedger and the
    SLO registry read (tenant, tokens, status, ttft/tpot, flight)."""

    def __init__(self, tenant=None, tokens=(1, 2), status="eos",
                 ttft=0.05, tpot=0.01, flight=None):
        self.tenant = tenant
        self.tokens = list(tokens)
        self.status = status
        self.ttft = ttft
        self.tpot = tpot
        self.flight = flight if flight is not None else {}


# ------------------------------------------------------------ jains_index
def test_jains_index_math_and_edges():
    assert jains_index([]) == 1.0            # nobody served, nobody starved
    assert jains_index([0.0, 0.0]) == 1.0
    assert jains_index([5.0, 5.0, 5.0]) == 1.0
    # one tenant takes everything: 1/n exactly
    assert jains_index([10.0, 0.0]) == pytest.approx(0.5)
    assert jains_index([10.0, 0.0, 0.0, 0.0]) == pytest.approx(0.25)
    # monotone: a more even split scores higher
    assert jains_index([8.0, 2.0]) < jains_index([6.0, 4.0]) < 1.0


def test_tenant_name_folds_none_to_default():
    assert tenant_name(None) == DEFAULT_TENANT == "default"
    assert tenant_name("acme") == "acme"


# ------------------------------------------------- VirtualTokenCounter
def test_vtc_charges_weighted_service():
    vtc = VirtualTokenCounter(prefill_weight=0.5)
    # decode tokens at full price, prefill discounted
    assert vtc.charge("a", decode=10) == pytest.approx(10.0)
    assert vtc.charge("a", prefill=8) == pytest.approx(14.0)
    assert vtc.service("a") == pytest.approx(14.0)
    assert vtc.service("missing") == 0.0
    # None folds to the default tenant everywhere (fresh counter so the
    # floor lift does not muddy the arithmetic)
    vtc2 = VirtualTokenCounter()
    vtc2.charge(None, decode=3)
    assert vtc2.service(None) == vtc2.service("default") \
        == pytest.approx(3.0)


def test_vtc_floor_lift_on_late_registration():
    """A tenant arriving after others have accrued service starts at
    the current FLOOR, not zero — idle hours must not bank a credit
    that lets it monopolize the fleet until the books catch up."""
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    vtc.touch("late")
    assert vtc.service("late") == pytest.approx(100.0)
    # the floor is the MINIMUM live counter, not the max
    vtc.charge("late", decode=20)
    vtc.touch("later-still")
    assert vtc.service("later-still") == pytest.approx(100.0)
    # touch() never charges: repeated sightings are free
    vtc.touch("late")
    assert vtc.service("late") == pytest.approx(120.0)


def test_vtc_weights_scale_accrual():
    """A weight-2 tenant accrues at half rate: fair ordering then
    grants it twice the tokens — paid tiers without a second knob."""
    vtc = VirtualTokenCounter(weights={"paid": 2.0})
    vtc.touch("paid")    # register both before charging: otherwise the
    vtc.touch("free")    # second inherits the first's floor lift
    vtc.charge("paid", decode=100)
    vtc.charge("free", decode=100)
    assert vtc.service("paid") == pytest.approx(50.0)
    assert vtc.service("free") == pytest.approx(100.0)
    assert vtc.least_served(["paid", "free"]) == "paid"
    with pytest.raises(ValueError):
        VirtualTokenCounter(weights={"bad": 0.0})
    with pytest.raises(ValueError):
        VirtualTokenCounter(prefill_weight=-0.1)


def test_vtc_enforcement_queries_and_tie_break():
    vtc = VirtualTokenCounter()
    vtc.charge("a", decode=5)
    vtc.charge("b", decode=50)
    vtc.touch("c")   # floor-lifted to 5
    assert vtc.least_served(["a", "b", "c"]) == "a"    # 5 ties 5: name
    assert vtc.most_over_served(["a", "b", "c"]) == "b"
    # None candidates stay None so callers can match raw labels
    assert vtc.least_served([None]) is None
    snap = vtc.snapshot()
    assert set(snap) == {"service", "share", "fairness_index"}
    assert sum(snap["share"].values()) == pytest.approx(1.0)
    assert snap["fairness_index"] == pytest.approx(
        jains_index(snap["service"].values()))


# ------------------------------------------- scheduler fair head rotate
class _IdleEngine:
    """Minimal engine surface for queue-only Scheduler tests: no free
    slots, so _admit never dispatches and the queue is observable."""

    class config:
        decode_burst = 1

    num_free = 0


def _queued_sched(vtc):
    sched = Scheduler(_IdleEngine(), clock=FakeClock(), max_queue=16,
                      vtc=vtc)
    for rid, tenant in enumerate(["a", "b", "a", "b"]):
        sched.submit(Request(rid=rid, prompt=[1, 2], max_new_tokens=4,
                             tenant=tenant))
    return sched


def test_fair_head_rotates_least_served_tenants_earliest_request():
    vtc = VirtualTokenCounter()
    sched = _queued_sched(vtc)
    vtc.charge("a", decode=100)      # b is now starved
    sched._rotate_fair_head()
    # b's EARLIEST request comes to the head; within-tenant FIFO holds
    assert [r.rid for r in sched.queue] == [1, 0, 2, 3]
    # idempotent while the service picture is unchanged
    sched._rotate_fair_head()
    assert [r.rid for r in sched.queue] == [1, 0, 2, 3]


def test_fair_head_service_tie_degrades_to_arrival_order():
    vtc = VirtualTokenCounter()
    sched = _queued_sched(vtc)       # submit touched both at floor 0
    sched._rotate_fair_head()
    assert [r.rid for r in sched.queue] == [0, 1, 2, 3]


def test_no_vtc_is_byte_identical_fifo():
    """The off switch: without a vtc the rotation is a no-op and
    submit never touches any counter — the default path is FIFO."""
    sched = _queued_sched(None)
    sched._rotate_fair_head()
    assert [r.rid for r in sched.queue] == [0, 1, 2, 3]


def test_fair_head_single_tenant_queue_is_untouched():
    vtc = VirtualTokenCounter()
    sched = Scheduler(_IdleEngine(), clock=FakeClock(), max_queue=16,
                      vtc=vtc)
    for rid in range(3):
        sched.submit(Request(rid=rid, prompt=[1], max_new_tokens=4,
                             tenant="only"))
    vtc.charge("only", decode=10)
    sched._rotate_fair_head()
    assert [r.rid for r in sched.queue] == [0, 1, 2]


def test_scheduler_submit_registers_tenant_at_floor():
    vtc = VirtualTokenCounter()
    vtc.charge("old", decode=40)
    sched = Scheduler(_IdleEngine(), clock=FakeClock(), max_queue=16,
                      vtc=vtc)
    sched.submit(Request(rid=0, prompt=[1], max_new_tokens=4,
                         tenant="new"))
    assert vtc.service("new") == pytest.approx(40.0)


# --------------------------------------------- admission: fairness gate
def test_admission_refuses_most_over_served_under_pressure():
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    vtc.touch("acme")
    ac = AdmissionController(vtc=vtc, fair_max_inflight=2)
    assert ac.try_acquire("bulk") == (True, None)   # below pressure
    assert ac.try_acquire("acme") == (True, None)
    # at pressure, two tenants competing: the over-served one is
    # refused with the TYPED reason, the starved one still gets in
    assert ac.try_acquire("bulk") == (False, "fairness")
    assert ac.refused["fairness"] == 1
    assert ac.try_acquire("acme") == (True, None)
    # releases relieve the pressure and the gate opens again
    ac.release("acme")
    ac.release("acme")
    assert ac.try_acquire("bulk") == (True, None)


def test_admission_fairness_needs_two_competing_tenants():
    """One tenant alone poses a capacity question, not a fairness one —
    that is the rate/concurrency envelopes' job."""
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    ac = AdmissionController(vtc=vtc, fair_max_inflight=2)
    assert ac.try_acquire("bulk") == (True, None)
    assert ac.try_acquire("bulk") == (True, None)
    assert ac.try_acquire("bulk") == (True, None)   # pressure, no rival
    assert ac.refused["fairness"] == 0


def test_admission_fairness_off_without_vtc_or_pressure_knob():
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    for ac in (AdmissionController(fair_max_inflight=2),
               AdmissionController(vtc=vtc)):
        assert ac.try_acquire("bulk") == (True, None)
        assert ac.try_acquire("acme") == (True, None)
        assert ac.try_acquire("bulk") == (True, None)
        assert ac.refused["fairness"] == 0


def test_admission_concurrency_checked_before_fairness():
    """A tenant over its own cap must not also burn a fairness refusal
    (or a rate token) for a request that was never going to run."""
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    vtc.touch("acme")
    ac = AdmissionController(
        {"bulk": TenantPolicy(max_concurrent=1)},
        vtc=vtc, fair_max_inflight=1)
    assert ac.try_acquire("bulk") == (True, None)
    assert ac.try_acquire("acme") == (True, None)
    assert ac.try_acquire("bulk") == (False, "concurrency")
    assert ac.refused == {"rate": 0, "concurrency": 1, "fairness": 0}


def test_admission_acquire_touches_vtc_floor():
    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=30)
    ac = AdmissionController(vtc=vtc, fair_max_inflight=8)
    ac.try_acquire("fresh")
    assert vtc.service("fresh") == pytest.approx(30.0)


# ------------------------------------------------------- TenantLedger
def test_ledger_meters_cost_per_tenant():
    ledger = TenantLedger()
    flight = {"queue_s": 0.1, "prefill_s": 0.2, "decode_s": 0.3,
              "stall_s": 0.0, "prefix_hit_tokens": 4}
    ledger.on_completion(_C(tenant="acme", tokens=[1, 2, 3],
                            flight=flight), prompt_tokens=10)
    ledger.on_completion(_C(tenant="acme", status="error", tokens=[],
                            ttft=None, tpot=None), prompt_tokens=5)
    ledger.on_completion(_C(tenant=None, tokens=[1]), prompt_tokens=2)
    rep = ledger.report()
    acme = rep["tenants"]["acme"]
    assert acme["requests"] == {"eos": 1, "error": 1}
    assert acme["prompt_tokens"] == 15
    assert acme["output_tokens"] == 3
    assert acme["prefix_hit_tokens"] == 4
    assert acme["seconds"]["decode_s"] == pytest.approx(0.3)
    assert acme["ttft_s"] == percentile_summary([0.05])
    # raw tails ride along for fleet pooling (never p-of-p)
    assert rep["samples"]["acme"]["ttft_s"] == [0.05]
    # the unlabeled tenant is one named tenant, not a None key
    assert rep["tenants"]["default"]["output_tokens"] == 1


def test_ledger_bills_prefill_from_flight_stamp_fallback():
    """A worker-side ledger has no request back-pointer: the flight
    record's prompt_tokens stamp (scheduler _finish) still bills it."""
    ledger = TenantLedger()
    ledger.on_completion(_C(tenant="t", flight={"prompt_tokens": 7}))
    assert ledger.report()["tenants"]["t"]["prompt_tokens"] == 7
    # an explicit caller value wins over the stamp
    ledger.on_completion(_C(tenant="t", flight={"prompt_tokens": 7}),
                         prompt_tokens=3)
    assert ledger.report()["tenants"]["t"]["prompt_tokens"] == 10


def test_ledger_report_shares_with_and_without_vtc():
    vtc = VirtualTokenCounter()
    vtc.touch("a")
    vtc.touch("b")
    vtc.charge("a", decode=30)
    vtc.charge("b", decode=10)
    rep = TenantLedger(vtc=vtc).report()
    assert rep["share"]["a"] == pytest.approx(0.75)
    assert rep["fairness_index"] == pytest.approx(
        jains_index([30.0, 10.0]))
    # fair mode off: metering still answers, over raw output tokens
    ledger = TenantLedger()
    ledger.on_completion(_C(tenant="a", tokens=[1, 2, 3]))
    ledger.on_completion(_C(tenant="b", tokens=[1]))
    rep = ledger.report()
    assert rep["service"] == {"a": 3.0, "b": 1.0}
    assert rep["fairness_index"] == pytest.approx(jains_index([3, 1]))


def test_ledger_exports_tenant_counters_to_registry():
    reg = MetricsRegistry()
    vtc = VirtualTokenCounter()
    vtc.charge("acme", decode=2)
    ledger = TenantLedger(registry=reg, vtc=vtc)
    ledger.on_completion(
        _C(tenant="acme", tokens=[1, 2],
           flight={"decode_s": 0.5}), prompt_tokens=6)
    snap = reg.snapshot()
    assert snap["tenant_requests_total{status=eos,tenant=acme}"] == 1
    assert snap["tenant_prompt_tokens_total{tenant=acme}"] == 6
    assert snap["tenant_output_tokens_total{tenant=acme}"] == 2
    assert snap["tenant_cost_seconds_total{phase=decode_s,tenant=acme}"] \
        == pytest.approx(0.5)
    assert snap["tenant_fairness_index"] == pytest.approx(1.0)


# ------------------------------------------- fleet federation (rollup)
def test_federate_tenant_reports_sums_pools_and_rederives():
    def _rep(ttft, out_tokens, service):
        return {
            "tenants": {"t": {
                "requests": {"eos": 1}, "prompt_tokens": 2,
                "output_tokens": out_tokens, "prefix_hit_tokens": 0,
                "seconds": {"queue_s": 0.1, "prefill_s": 0.0,
                            "decode_s": 0.0, "stall_s": 0.0},
            }},
            "samples": {"t": {"ttft_s": ttft, "tpot_s": []}},
            "service": {"t": service},
        }

    out = federate_tenant_reports([
        _rep([0.01, 0.02], 3, 5.0), _rep([0.5], 4, 7.0),
        "not-a-dict",   # a worker that answered garbage is skipped
    ])
    t = out["tenants"]["t"]
    assert t["requests"] == {"eos": 2}
    assert t["output_tokens"] == 7
    assert t["seconds"]["queue_s"] == pytest.approx(0.2)
    # pooled percentiles over the union, never p-of-p
    assert t["ttft_s"] == percentile_summary([0.01, 0.02, 0.5])
    assert out["service"]["t"] == pytest.approx(12.0)
    assert out["share"]["t"] == pytest.approx(1.0)
    assert out["fairness_index"] == pytest.approx(1.0)
    # empty input is a valid (vacuously fair) fleet
    empty = federate_tenant_reports([])
    assert empty["tenants"] == {} and empty["fairness_index"] == 1.0


# --------------------------------------------------- TenantSLORegistry
SLO_CFG = SLOConfig(
    error_rate=0.1, fast_window_s=1.0, slow_window_s=5.0,
    trip_burn=2.0, resolve_burn=1.0, min_events=3,
)


def _burn(reg, tenant, n=5, status="error", t0=0.0):
    for i in range(n):
        reg.observe_event(tenant=tenant, t=t0 + i * 0.01, status=status)


def test_tenant_slo_isolation_one_budget_each():
    """The whole point of the registry: the hostile tenant's burn trips
    ITS alert; the compliant tenant's budget never notices."""
    mreg = MetricsRegistry()
    reg = TenantSLORegistry(SLO_CFG, registry=mreg)
    _burn(reg, "bulk", status="error")
    _burn(reg, "acme", status="length")
    reg.evaluate(0.1)
    assert reg.is_burning("bulk")
    assert not reg.is_burning("acme")
    assert reg.burning_tenants() == ["bulk"]
    assert reg.active   # the router's single-watchdog view still works
    # alert history carries the tenant as a 4th element
    assert [(e, o, t) for _, e, o, t in reg.alert_log] \
        == [("trip", "error_rate", "bulk")]
    # burn gauges are tenant-labelled
    snap = mreg.snapshot()
    assert snap[
        "slo_burn_rate{objective=error_rate,tenant=bulk,window=fast}"] \
        == 10.0
    assert snap[
        "slo_burn_rate{objective=error_rate,tenant=acme,window=fast}"] \
        == 0.0


def test_tenant_slo_burn_signal_is_worst_across_tenants():
    reg = TenantSLORegistry(SLO_CFG)
    _burn(reg, "bulk", status="error")
    _burn(reg, "acme", status="length")
    reg.evaluate(0.1)
    sig = reg.burn_signal()
    assert sig["burn_fast"] == 10.0      # bulk's, not an average
    assert sig["active"] and not sig["resolved"]
    # empty registry: quiet signal, vacuously resolved
    empty = TenantSLORegistry(SLO_CFG).burn_signal()
    assert empty == {"burn_fast": 0.0, "burn_slow": 0.0,
                     "active": False, "resolved": True}


def test_tenant_slo_none_folds_to_default_tenant():
    reg = TenantSLORegistry(SLO_CFG)
    _burn(reg, None, status="error")
    reg.evaluate(0.1)
    assert reg.burning_tenants() == ["default"]
    assert reg.is_burning(None) and reg.is_burning("default")


def test_tenant_slo_overflow_shares_one_watchdog():
    """Past max_tenants, newcomers share the "other" dog — bounded
    cardinality; over-cap tenants answer for (and to) each other."""
    reg = TenantSLORegistry(SLO_CFG, max_tenants=2)
    reg.watchdog("a")
    reg.watchdog("b")
    assert reg.watchdog("c") is reg.watchdog("d")
    assert reg.watchdog("c").tenant == "other"
    assert reg.watchdog("a") is not reg.watchdog("b")
    _burn(reg, "c", status="error")
    reg.evaluate(0.1)
    assert reg.burning_tenants() == ["other"]
    # is_burning maps unseen names through the fold (price of the cap)
    assert reg.is_burning("c") and reg.is_burning("zzz")
    assert not reg.is_burning("a")


def test_tenant_slo_is_burning_never_creates_a_watchdog():
    reg = TenantSLORegistry(SLO_CFG)
    assert not reg.is_burning("ghost")
    assert reg.evaluate(0.1) == {}


def test_tenant_slo_per_tenant_overrides():
    reg = TenantSLORegistry(
        SLO_CFG, overrides={"batch": SLOConfig(
            error_rate=0.5, min_events=3)})
    assert reg.watchdog("batch").config.error_rate == 0.5
    assert reg.watchdog("acme").config.error_rate == 0.1


# ------------------------------------- tenant-scoped brown-out shedding
def test_replica_handle_shed_covers_only_named_tenants():
    from ddp_practice_tpu.serve.router import ReplicaHandle

    sched = Scheduler(_IdleEngine(), clock=FakeClock(), max_queue=16)
    h = ReplicaHandle(0, sched)
    specs = [  # (rid, tenant, priority)
        (0, "bulk", 1), (1, "acme", 1), (2, "bulk", 0), (3, "bulk", 2),
    ]
    for rid, tenant, prio in specs:
        sched.submit(Request(rid=rid, prompt=[1], max_new_tokens=4,
                             tenant=tenant, priority=prio))
    rids = h.shed_queued(1, covers=lambda t: t == "bulk")
    # only the burning tenant's SHEDDABLE work goes: acme keeps its
    # slot, bulk's priority-0 interactive request is never shed
    assert rids == [0, 3]
    assert [r.rid for r in sched.queue] == [1, 2]
    # the shed sub-completions are consumed here (watermark advanced):
    # the router finalizes from the rids, not from poll()
    assert h.consumed == len(sched.completions) == 2
    assert all(c.status == "shed" for c in sched.completions)
    # covers=None is the global brown-out: everything eligible goes
    assert h.shed_queued(1, covers=None) == [1]


def test_remote_shed_ships_tenant_names_not_the_predicate():
    """A callable cannot cross the RPC wire: the remote form of a
    scoped shed is the tenants name-list kw, and only when scoped —
    a global shed stays byte-compatible with pre-QoS workers."""
    from ddp_practice_tpu.serve.supervisor import RemoteReplicaHandle

    class _FakeClient:
        def __init__(self):
            self.calls = []

        def call(self, op, **kw):
            self.calls.append((op, kw))
            return {"rids": [7]}

    h = RemoteReplicaHandle.__new__(RemoteReplicaHandle)
    h.outstanding = {7: {}}
    h._shed_skip = set()
    fake = _FakeClient()
    h._client = lambda: fake
    rids = h.shed_queued(1, covers=lambda t: t == "bulk",
                         tenants=["bulk"])
    assert fake.calls == [("shed", {"min_priority": 1,
                                    "tenants": ["bulk"]})]
    assert rids == [7]
    assert 7 in h._shed_skip and 7 not in h.outstanding
    fake.calls.clear()
    h.shed_queued(2, covers=None, tenants=None)
    assert fake.calls == [("shed", {"min_priority": 2})]


# --------------------- cardinality cap end-to-end (worker -> federated)
def test_tenant_label_cardinality_folds_to_other_fleet_wide():
    """>64 distinct tenants on one worker: the 65th+ tenant's METRICS
    fold to tenant=other at the label guard, and the fold survives the
    worker /metrics -> ScrapeFederator relabel into the fleet page.
    The /tenants rollup keeps raw names (bounded by the ledger window,
    not the metric plane's cardinality cap)."""
    from ddp_practice_tpu.utils.telemetry import (
        ScrapeFederator,
        TelemetryServer,
    )

    reset_label_guard()
    srv = None
    try:
        reg = MetricsRegistry()
        ledger = TenantLedger(registry=reg)
        for i in range(70):
            ledger.on_completion(_C(tenant=f"t{i:03d}", tokens=[1]),
                                 prompt_tokens=1)
        srv = TelemetryServer(registry=reg, tenants_fn=ledger.report,
                              port=0)
        targets = {0: {"host": "127.0.0.1", "port": srv.port,
                       "up": True, "pid": 1, "state": "running",
                       "restarts": 0, "heartbeat_age_s": 0.0}}
        fed = ScrapeFederator(lambda: targets)

        def _tenants_in(text):
            return set(re.findall(
                r'tenant_requests_total\{[^}]*tenant="([^"]+)"', text))

        worker_text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=2
        ).read().decode()
        seen = _tenants_in(worker_text)
        assert len(seen) == 65 and "other" in seen   # 64 named + fold
        # the overflow bucket pools everyone past the cap
        assert 'tenant_requests_total{status="eos",tenant="other"} 6' \
            in worker_text
        fleet_text = fed.render_text()
        fleet_seen = _tenants_in(fleet_text)
        assert fleet_seen == seen                    # relabel preserves
        assert 'worker="0"' in fleet_text
        # the QoS rollup is NOT folded: all 70 raw names federate
        rollup = fed.tenants()
        assert len(rollup["tenants"]) == 70
        assert rollup["fairness_index"] == pytest.approx(1.0)
        assert rollup["workers"]["0"]["fairness_index"] \
            == pytest.approx(1.0)
    finally:
        if srv is not None:
            srv.close()
        reset_label_guard()


def test_slo_registry_tenant_gauges_respect_label_guard():
    """A hostile tenant-id space must not mint unbounded gauge
    families even below the registry's own max_tenants cap."""
    reset_label_guard()
    old = set_label_limit(3)
    try:
        mreg = MetricsRegistry()
        reg = TenantSLORegistry(SLO_CFG, registry=mreg, max_tenants=64)
        for i in range(6):
            _burn(reg, f"t{i}", status="error")
        reg.evaluate(0.1)
        burn_keys = [k for k in mreg.snapshot()
                     if k.startswith("slo_burn_rate{")
                     and "window=fast" in k]
        values = {re.search(r"tenant=([^,}]+)", k).group(1)
                  for k in burn_keys}
        assert len(values) == 4 and "other" in values   # 3 named + fold
    finally:
        set_label_limit(old)
        reset_label_guard()
