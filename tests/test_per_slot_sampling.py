"""Per-slot sampling (inference.sample_logits_batch + engine wiring).

The contract: ONE jitted decode program serves a batch mixing greedy
and sampled rows with arbitrary per-request (temperature, top_k,
top_p), bit-identical to the per-request `sample_logits` path, and
never recompiles when the params change — they are traced (b,) arrays,
not compile-time constants. The kernel-level pins are jit-free and run
in tier-1; everything that compiles an engine is `slow`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.inference import sample_logits, sample_logits_batch
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, SlotEngine
from ddp_practice_tpu.serve.engine import warm_engine

VOCAB = 32


# ------------------------------------------------------ kernel-level pins
@pytest.mark.fast
def test_batch_rows_bit_identical_to_per_request_sampler(devices):
    """Each row of sample_logits_batch == sample_logits called alone on
    that row with the same key and params — including the greedy row
    (temperature 0) and every filter combination."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, VOCAB)), jnp.float32)
    params = [(0.0, 0, 0.0),      # greedy
              (0.8, 5, 0.0),      # top-k only
              (1.2, 0, 0.9),      # top-p only
              (0.7, 3, 0.85)]     # composed k-then-p
    keys = jnp.stack([
        jax.random.PRNGKey(100 + i) for i in range(len(params))
    ])

    got = sample_logits_batch(
        logits, keys,
        temperature=jnp.asarray([p[0] for p in params]),
        top_k=jnp.asarray([p[1] for p in params]),
        top_p=jnp.asarray([p[2] for p in params]),
    )
    for i, (t, k, p) in enumerate(params):
        want = sample_logits(
            logits[i:i + 1], keys[i], temperature=t, top_k=k, top_p=p
        )[0]
        assert int(got[i]) == int(want), (i, params[i])


@pytest.mark.fast
def test_batch_sampler_row_independence(devices):
    """A row's draw depends only on its own key/params — reshuffling
    its batchmates' params must not move it (the property that lets
    the engine mix greedy and sampled requests in one dispatch)."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(3, VOCAB)), jnp.float32)
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(3)])

    def draw(neighbors):
        t = jnp.asarray([0.9, neighbors[0], neighbors[1]])
        k = jnp.asarray([4, 0, 7])
        p = jnp.asarray([0.0, 0.95, 0.5])
        return int(sample_logits_batch(
            logits, keys, temperature=t, top_k=k, top_p=p)[0])

    assert draw((0.0, 1.5)) == draw((2.0, 0.3))


@pytest.mark.fast
def test_batch_sampler_edge_params(devices):
    """top_k past the vocab is a no-op filter (clamped), negative
    temperature is greedy, and greedy ignores its key entirely."""
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(2, VOCAB)), jnp.float32)
    keys = jnp.stack([jax.random.PRNGKey(5), jax.random.PRNGKey(6)])
    a = sample_logits_batch(
        logits, keys, temperature=jnp.asarray([0.8, -1.0]),
        top_k=jnp.asarray([VOCAB + 50, 0]), top_p=jnp.zeros(2))
    b = sample_logits_batch(
        logits, keys, temperature=jnp.asarray([0.8, 0.0]),
        top_k=jnp.asarray([0, 0]), top_p=jnp.zeros(2))
    assert int(a[0]) == int(b[0])            # over-vocab k == no filter
    assert int(a[1]) == int(b[1]) == int(jnp.argmax(logits[1]))
    other = jnp.stack([keys[0], jax.random.PRNGKey(7)])
    c = sample_logits_batch(
        logits, other, temperature=jnp.asarray([0.8, 0.0]),
        top_k=jnp.zeros(2, jnp.int32), top_p=jnp.zeros(2))
    assert int(c[1]) == int(b[1])            # greedy row is key-blind


# ---------------------------------------------------------- engine wiring
@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


SKW = dict(max_slots=3, prompt_buckets=(8,), max_len=64)


def _run_slot(eng, prompt, n=10, seed=7, sampling=None):
    kw = {} if sampling is None else {"sampling": sampling}
    slot = eng.admit(prompt, seed=seed, **kw)
    out = []
    for _ in range(n):
        out.append(int(eng.step_burst()[0][slot]))
    eng.release(slot)
    return out


@pytest.mark.slow
def test_per_slot_stream_identical_to_config_baked_engine(lm, devices,
                                                          compile_guard):
    """A slot sampled at (t, k, p) in the per-slot engine emits the
    same stream as a legacy engine with those params BAKED into its
    decode program — and a greedy-override slot matches a plain greedy
    engine. Then the churn pin: admit/decode/release across wildly
    different per-slot params compiles NOTHING new."""
    model, params = lm
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, VOCAB, 7).tolist()

    legacy = SlotEngine(model, params, EngineConfig(
        **SKW, temperature=0.8, top_k=5, top_p=0.9))
    warm_engine(legacy)
    ps = SlotEngine(model, params, EngineConfig(
        **SKW, per_slot_sampling=True))
    warm_engine(ps)
    greedy = SlotEngine(model, params, EngineConfig(**SKW))
    warm_engine(greedy)

    assert _run_slot(legacy, prompt) == _run_slot(
        ps, prompt, sampling=(0.8, 5, 0.9))
    g = _run_slot(greedy, prompt)
    assert g == _run_slot(ps, prompt, sampling=(0.0, 0, 0.0))
    assert g == _run_slot(ps, prompt)   # defaults = config (greedy)

    with compile_guard(ps):
        slots = [ps.admit(prompt, seed=s, sampling=samp)
                 for s, samp in ((1, (0.0, 0, 0.0)),
                                 (2, (1.3, 7, 0.0)),
                                 (3, (0.5, 0, 0.95)))]
        ps.step_burst()
        for s in slots:
            ps.release(s)


@pytest.mark.slow
def test_sampling_override_without_flag_raises(lm, devices):
    """Silently decoding at the WRONG params is the one outcome this
    must never produce: the legacy engine bakes config params into its
    decode program, so a per-request override it cannot honor raises
    at admit — and leaves no slot half-admitted."""
    model, params = lm
    eng = SlotEngine(model, params, EngineConfig(**SKW))
    warm_engine(eng)
    prompt = [1, 2, 3, 4]
    with pytest.raises(ValueError, match="per_slot_sampling"):
        eng.admit(prompt, sampling=(0.7, 0, 0.0))
    assert eng.num_active == 0
    # config-matching overrides are fine (they change nothing)
    slot = eng.admit(prompt, sampling=(0.0, 0, 0.0))
    eng.release(slot)


def test_spec_decode_excludes_per_slot_sampling(lm, devices):
    """Exact speculative acceptance is greedy string matching; the
    combination is rejected at construction, before any compile."""
    from ddp_practice_tpu.serve import PagedEngine

    model, params = lm
    with pytest.raises(ValueError, match="per_slot_sampling"):
        PagedEngine(model, params, EngineConfig(
            max_slots=2, prompt_buckets=(8,), max_len=64,
            block_size=8, max_blocks_per_slot=10,
            spec_decode=True, per_slot_sampling=True))
