"""Ring attention correctness: exact match against full attention.

Sequence parallelism is absent from the reference (SURVEY §5.7); here it is
first-class, so it gets an exactness contract: blockwise online-softmax
attention with K/V rotating over the 'seq' mesh axis must equal the dense
computation, causal and non-causal, to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.ops.attention import _attention
from ddp_practice_tpu.parallel.mesh import build_mesh
from ddp_practice_tpu.parallel.ring import ring_attention, set_current_mesh


@pytest.fixture()
def seq_mesh(devices):
    mesh = build_mesh(MeshConfig(data=1, seq=8, tensor=1))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(None)


def _qkv(b=2, s=32, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.fast
def test_ring_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    dense = _attention(q, k, v, causal=causal)
    ring = ring_attention(q, k, v, axis_name="seq", causal=causal)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_ring_inside_jit(seq_mesh):
    q, k, v = _qkv(seed=1)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, axis_name="seq")

    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(_attention(q, k, v, causal=False)),
        rtol=2e-5, atol=2e-5,
    )


def test_ring_grad_matches_dense(seq_mesh):
    q, k, v = _qkv(seed=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, axis_name="seq") ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_attention(q, k, v, causal=False) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(seq_mesh, causal):
    """The Pallas kernel as the per-block local attention inside the ring
    (the flash x sequence-parallel composition, VERDICT weak #4)."""
    q, k, v = _qkv(seed=3)
    dense = _attention(q, k, v, causal=causal)
    ring = ring_attention(q, k, v, axis_name="seq", causal=causal,
                          impl="flash")
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grad_matches_dense(seq_mesh, causal):
    q, k, v = _qkv(seed=4)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, axis_name="seq", causal=causal,
                           impl="flash") ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(_attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )
