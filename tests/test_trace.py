"""Request-lifecycle tracing (utils/trace.py), the Chrome-trace
validator (tools/check_traces.py), the profile_region re-entrancy fix,
and the serve-stack instrumentation — including the ISSUE-4 acceptance
pin: a crash-migrated request's spans on the SURVIVOR replica carry the
original trace_id, and the exported trace is validator-clean.

Everything deterministic: recorder units run on hand-advanced clocks,
the serving integration runs FakeClock replicas with a seeded FaultPlan.
"""

import json
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
from tools.check_traces import validate  # noqa: E402

from ddp_practice_tpu.utils.trace import (  # noqa: E402
    ENGINE_LANE,
    ROUTER_PID,
    SLOT_LANE_BASE,
    TraceRecorder,
    label_replica,
)


class ManualClock:
    def __init__(self, start=0.0):
        self.t = start

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------- recorder
@pytest.mark.fast
def test_recorder_spans_instants_and_export_validate():
    clk = ManualClock()
    r = TraceRecorder(clock=clk)
    r.set_process_name(0, "test")
    with r.span("outer", pid=0, tid=0, step=1):
        clk.advance(0.5)
        with r.span("inner", pid=0, tid=0):
            clk.advance(0.25)
        r.instant("tick", pid=0, tid=0, n=3)
        clk.advance(0.25)
    r.record_async("request", 0.0, 1.0, trace_id="r1", pid=0,
                   attrs={"status": "eos"})
    trace = r.to_chrome_trace()
    assert validate(trace) == []
    events = trace["traceEvents"]
    by = {(e["ph"], e["name"]): e for e in events}
    assert by[("B", "outer")]["ts"] == 0.0
    assert by[("E", "outer")]["ts"] == pytest.approx(1e6)
    assert by[("B", "inner")]["ts"] == pytest.approx(0.5e6)
    assert by[("B", "outer")]["args"]["step"] == 1
    assert by[("i", "tick")]["args"]["n"] == 3
    assert by[("b", "request")]["id"] == "r1"
    assert by[("e", "request")]["ts"] == pytest.approx(1e6)


@pytest.mark.fast
def test_recorder_ring_buffer_bounds_memory():
    r = TraceRecorder(clock=ManualClock(), max_events=16)
    for i in range(1000):
        r.instant(f"e{i}", pid=0)
    assert len(r) == 16
    # the ring keeps the most RECENT window (flight recorder, not archive)
    names = [e["name"] for e in r.to_chrome_trace()["traceEvents"]
             if e["ph"] == "i"]
    assert names == [f"e{i}" for i in range(984, 1000)]


@pytest.mark.fast
def test_disabled_recorder_is_noop():
    r = TraceRecorder(clock=ManualClock(), enabled=False)
    s1 = r.span("a", pid=0)
    s2 = r.span("b", pid=0)
    assert s1 is s2  # the shared null context — no per-span allocation
    with s1:
        pass
    r.instant("x", pid=0)
    r.record_async("request", 0.0, 1.0, trace_id="r0", pid=0)
    assert len(r) == 0
    r.enable()
    r.instant("y", pid=0)
    assert len(r) == 1


@pytest.mark.fast
def test_zero_duration_spans_still_nest_cleanly():
    """FakeClock spans can begin and end at the same instant, and one
    lane can host several of them back to back (slot freed and re-
    admitted inside one tick) — the exporter must still emit matched,
    ordered B/E pairs."""
    clk = ManualClock()
    r = TraceRecorder(clock=clk)
    r.set_process_name(0, "p")
    with r.span("a", pid=0, tid=1):
        pass
    with r.span("b", pid=0, tid=1):
        pass
    # and an enclosing + enclosed pair sharing both endpoints
    r.record_span("outer", 1.0, 1.0, pid=0, tid=2)
    r.record_span("inner", 1.0, 1.0, pid=0, tid=2)
    assert validate(r.to_chrome_trace()) == []


@pytest.mark.fast
def test_recorder_thread_safety_smoke():
    r = TraceRecorder(clock=ManualClock(), max_events=10_000)

    def worker(k):
        for i in range(500):
            with r.span(f"w{k}", pid=k):
                pass

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(r) == 2000
    for k in range(4):
        r.set_process_name(k, f"w{k}")
    assert validate(r.to_chrome_trace()) == []


@pytest.mark.fast
def test_save_writes_loadable_json(tmp_path):
    r = TraceRecorder(clock=ManualClock())
    r.set_process_name(0, "p")
    with r.span("s", pid=0):
        pass
    path = tmp_path / "t.json"
    r.save(str(path))
    assert validate(json.loads(path.read_text())) == []


# -------------------------------------------------------------- validator
def _meta(pid):
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"p{pid}"}}


@pytest.mark.fast
def test_validator_catches_corruptions():
    def ev(ph, name, ts, pid=0, tid=0, **kw):
        return {"ph": ph, "name": name, "ts": ts, "pid": pid,
                "tid": tid, **kw}

    assert validate([]) != []  # not even an object
    assert validate({"traceEvents": "nope"}) != []
    # unclosed B
    errs = validate({"traceEvents": [_meta(0), ev("B", "a", 1.0)]})
    assert any("unclosed" in e for e in errs)
    # E name mismatch
    errs = validate({"traceEvents": [
        _meta(0), ev("B", "a", 1.0), ev("E", "b", 2.0)]})
    assert any("mismatch" in e for e in errs)
    # unknown pid (no process_name metadata)
    errs = validate({"traceEvents": [
        ev("B", "a", 1.0, pid=7), ev("E", "a", 2.0, pid=7)]})
    assert any("process_name" in e for e in errs)
    # lane ts goes backwards (crossing intervals)
    errs = validate({"traceEvents": [
        _meta(0), ev("B", "a", 5.0), ev("E", "a", 4.0)]})
    assert any("backwards" in e for e in errs)
    # async e without b
    errs = validate({"traceEvents": [_meta(0), ev("e", "r", 1.0, id="x")]})
    assert any("no open b" in e for e in errs)
    # non-finite / negative ts
    errs = validate({"traceEvents": [_meta(0), ev("i", "x", float("nan"))]})
    assert any("finite" in e for e in errs)
    errs = validate({"traceEvents": [_meta(0), ev("i", "x", -1.0)]})
    assert any("negative" in e for e in errs)
    # a clean one for contrast
    assert validate({"traceEvents": [
        _meta(0), ev("B", "a", 1.0), ev("E", "a", 2.0),
        ev("b", "r", 1.0, id="x"), ev("e", "r", 3.0, id="x"),
    ]}) == []


# -------------------------------------------------- profile_region fix
@pytest.fixture
def fake_profiler(monkeypatch):
    """Stub jax.profiler start/stop so the re-entrancy/exception
    contract is testable CPU-safely (no real capture, no trace dirs)."""
    from ddp_practice_tpu.utils import profiling

    calls = {"start": [], "stop": 0, "stop_error": None}

    def start_trace(d):
        if calls["start"] and calls["stop"] < len(calls["start"]):
            raise RuntimeError("profiler already started")
        calls["start"].append(d)

    def stop_trace():
        calls["stop"] += 1
        if calls["stop_error"] is not None:
            raise calls["stop_error"]

    monkeypatch.setattr(profiling.jax.profiler, "start_trace", start_trace)
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace", stop_trace)
    monkeypatch.setattr(profiling, "_trace_active", False)
    return calls


@pytest.mark.fast
def test_profile_region_nested_dirs_single_capture(fake_profiler):
    """Nested regions that BOTH pass profile_dir: one start, one stop,
    no 'profiler already started' crash (the inner annotates only)."""
    from ddp_practice_tpu.utils.profiling import profile_region

    with profile_region("outer", profile_dir="/tmp/a"):
        with profile_region("inner", profile_dir="/tmp/b"):
            pass
        with profile_region("inner2", profile_dir="/tmp/c"):
            pass
    assert fake_profiler["start"] == ["/tmp/a"]
    assert fake_profiler["stop"] == 1
    # and a later region can capture again
    with profile_region("next", profile_dir="/tmp/d"):
        pass
    assert fake_profiler["start"] == ["/tmp/a", "/tmp/d"]


@pytest.mark.fast
def test_profile_region_body_exception_not_masked(fake_profiler):
    """The body's exception propagates even when stop_trace ALSO fails
    on the way out (the old finally swallowed the real error)."""
    from ddp_practice_tpu.utils.profiling import profile_region

    fake_profiler["stop_error"] = RuntimeError("flush failed")
    with pytest.raises(ValueError, match="the real bug"):
        with profile_region("r", profile_dir="/tmp/a"):
            raise ValueError("the real bug")
    assert fake_profiler["stop"] == 1  # stop was attempted
    # the failed stop must not wedge later regions into annotate-only
    fake_profiler["stop_error"] = None
    with profile_region("again", profile_dir="/tmp/b"):
        pass
    assert fake_profiler["start"] == ["/tmp/a", "/tmp/b"]


@pytest.mark.fast
def test_profile_region_stop_failure_alone_raises(fake_profiler):
    """With a healthy body, a stop_trace failure is real signal."""
    from ddp_practice_tpu.utils.profiling import profile_region

    fake_profiler["stop_error"] = RuntimeError("flush failed")
    with pytest.raises(RuntimeError, match="flush failed"):
        with profile_region("r", profile_dir="/tmp/a"):
            pass


@pytest.mark.fast
def test_profile_region_externally_started_profiler(fake_profiler):
    """A region opened while something else (train/loop.py's epoch
    window) already drives the profiler annotates only — and does NOT
    stop the capture it doesn't own."""
    from ddp_practice_tpu.utils.profiling import profile_region

    fake_profiler["start"].append("/external")  # simulate foreign capture
    with profile_region("r", profile_dir="/tmp/a"):
        pass
    assert fake_profiler["start"] == ["/external"]
    assert fake_profiler["stop"] == 0


# ------------------------------------------- serving integration (engine)
VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def test_scheduler_engine_spans_and_flight_records(lm):
    """One FakeClock replica: queued/request lifecycle spans, per-slot
    prefill lanes, decode-burst spans on the engine lane, flight records
    on every completion — and the export is validator-clean."""
    from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
    from ddp_practice_tpu.serve.scheduler import (
        FakeClock,
        Request,
        Scheduler,
    )

    model, params = lm
    clock = FakeClock(step_s=0.01)
    rec = TraceRecorder(clock=clock)
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=2, prompt_buckets=(4, 8), eos_id=None,
    ))
    engine.set_tracer(rec, 0)
    label_replica(rec, 0, 2)
    sched = Scheduler(engine, clock=clock, tracer=rec, replica=0)
    for rid in range(4):  # 4 requests on 2 slots: two must queue
        sched.submit(Request(rid=rid, prompt=[1, 2, 3],
                             max_new_tokens=4))
    comps = sched.run_until_idle()
    assert len(comps) == 4 and all(c.status == "length" for c in comps)

    # flight records: phases sum to (finish - arrival) by construction
    for c in comps:
        f = c.flight
        assert f is not None and f["retries"] == 0 and f["failovers"] == 0
        total = c.finish - c.arrival
        assert (f["queue_s"] + f["prefill_s"] + f["decode_s"]
                + f["stall_s"]) == pytest.approx(total)
        assert f["decode_s"] > 0
    # slots were contended: the late arrivals actually waited
    assert sum(c.flight["queue_s"] > 0 for c in comps) >= 2

    trace = rec.to_chrome_trace()
    assert validate(trace) == []
    events = trace["traceEvents"]
    prefills = [e for e in events if e["ph"] == "B"
                and e["name"] == "prefill"]
    bursts = [e for e in events if e["ph"] == "B"
              and e["name"] == "decode_burst"]
    assert len(prefills) == 4 and len(bursts) >= 8  # 4 tokens each, K=1
    # lane conventions: prefill on the slot lanes, bursts on the engine
    # lane, every span on this replica's pid
    assert {e["tid"] for e in prefills} <= {SLOT_LANE_BASE,
                                            SLOT_LANE_BASE + 1}
    assert all(e["tid"] == ENGINE_LANE for e in bursts)
    assert all(e["pid"] == 0 for e in prefills + bursts)
    # every request has its lifecycle async track
    req_ids = {e["id"] for e in events if e["ph"] == "b"
               and e["name"] == "request"}
    assert req_ids == {f"r{rid}" for rid in range(4)}
    # prefill spans carry the request's trace_id, and burst spans count
    # the batch occupancy they dispatched with
    assert {e["args"]["trace_id"] for e in prefills} == req_ids
    assert {e["args"]["active"] for e in bursts} <= {1, 2}


def test_tracer_off_records_nothing(lm):
    """tracer=None (the production default) leaves zero records and the
    engines' hot path un-annotated; flight records still attach."""
    from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
    from ddp_practice_tpu.serve.scheduler import (
        FakeClock,
        Request,
        Scheduler,
    )

    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=2, prompt_buckets=(4,), eos_id=None,
    ))
    sched = Scheduler(engine, clock=FakeClock(step_s=0.01))
    sched.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=2))
    comps = sched.run_until_idle()
    assert comps[0].flight is not None
    assert engine.tracer is None and not engine._slot_trace


def test_evacuate_reports_attempt_phases(lm):
    """The failover harvest carries each attempt's flight fragment —
    a crashed attempt never produces a Completion, so these phases are
    the ONLY record of its pre-crash queue/prefill/decode time (the
    router folds them in; without them the work would misreport as
    stall_s)."""
    from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
    from ddp_practice_tpu.serve.scheduler import (
        FakeClock,
        Request,
        Scheduler,
    )

    model, params = lm
    clock = FakeClock(step_s=0.01)
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=1, prompt_buckets=(4,), eos_id=None,
    ))
    sched = Scheduler(engine, clock=clock)
    sched.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=8))
    sched.submit(Request(rid=1, prompt=[3, 4], max_new_tokens=8))
    sched.step()  # admits rid 0 (1 slot); rid 1 waits in queue
    sched.step()
    clock.advance(0.05)
    ev = {req.rid: phases for req, _, _, phases in sched.evacuate()}
    assert set(ev) == {0, 1}
    # the running attempt: decoded for two ticks before the "crash"
    assert ev[0]["decode_s"] == pytest.approx(0.02 + 0.05)
    assert ev[0]["queue_s"] == 0.0 and ev[0]["prefill_s"] == 0.0
    # the queued attempt: all its time was queue wait
    assert ev[1]["decode_s"] == 0.0 and ev[1]["prefill_s"] == 0.0
    assert ev[1]["queue_s"] == pytest.approx(0.07)
    assert sched.idle


# --------------------------------- ISSUE-4 acceptance: failover linkage
@pytest.mark.chaos
def test_crash_migrated_request_keeps_trace_id_on_survivor(lm):
    """THE acceptance pin: under a chaos plan that kills replica 0
    mid-decode, the migrated requests' spans on the surviving replica
    carry the ORIGINAL trace_id — one request, one timeline across the
    crash — and the exported Chrome trace is validator-clean."""
    from ddp_practice_tpu.serve import (
        EngineConfig,
        FakeClock,
        FaultPlan,
        FaultSpec,
        Request,
        RouterConfig,
        make_router,
    )

    model, params = lm
    clock = FakeClock(step_s=0.01)
    rec = TraceRecorder(clock=clock)
    plan = FaultPlan([FaultSpec(kind="crash", tick=4, replica=0)])
    router = make_router(
        model, params, 2,
        EngineConfig(max_slots=2, prompt_buckets=(4, 8), eos_id=None),
        clock=clock, config=RouterConfig(seed=5), fault_plan=plan,
        tracer=rec,
    )
    for rid in range(4):
        router.submit(Request(rid=rid, prompt=[1 + rid, 2, 3],
                              max_new_tokens=8))
    comps = router.run_until_idle()
    assert len(comps) == 4
    assert all(c.status == "length" for c in comps)  # none lost
    migrated = [c for c in comps if c.flight["failovers"] >= 1]
    assert migrated, "the crash must have migrated at least one request"

    trace = rec.to_chrome_trace()
    assert validate(trace) == []
    events = trace["traceEvents"]

    dead = [e["args"]["replica"] for e in events
            if e["ph"] == "i" and e["name"] == "replica_dead"]
    assert dead == [0]
    survivor = 1
    for c in migrated:
        tid = f"r{c.rid}"
        # a failover instant on the router lane names this trace
        fo = [e for e in events if e["ph"] == "i" and e["name"] == "failover"
              and e["args"].get("trace_id") == tid]
        assert fo and all(e["pid"] == ROUTER_PID for e in fo)
        # and the SURVIVOR's prefill + request spans carry the original
        # trace_id: the re-admission joined the same timeline
        surv_prefills = [
            e for e in events if e["ph"] == "B" and e["name"] == "prefill"
            and e["pid"] == survivor
            and e["args"].get("trace_id") == tid
        ]
        assert surv_prefills, f"{tid}: no prefill span on the survivor"
        surv_request = [
            e for e in events if e["ph"] == "b" and e["name"] == "request"
            and e["pid"] == survivor and e["id"] == tid
        ]
        assert surv_request, f"{tid}: no request track on the survivor"
        # the flight record accounts the hop too
        assert c.flight["stall_s"] >= 0.0
    # router dispatch instants recorded the re-placements (>= one per
    # original placement plus one per migration)
    dispatches = [e for e in events
                  if e["ph"] == "i" and e["name"] == "dispatch"]
    assert len(dispatches) >= 4 + len(migrated)
    # token identity with a fault-free run is pinned in
    # tests/test_serve_router.py; here the TRACE is the contract


@pytest.mark.chaos
@pytest.mark.slow
def test_serve_bench_chaos_trace_out_end_to_end(tmp_path):
    """The CLI acceptance path (cli.py serve --replicas 2 --fault-plan
    ... --trace-out): real-clock bench, injected crash, trace written to
    disk, validator-clean, phase breakdown in the report."""
    from ddp_practice_tpu.serve.bench import serve_bench
    from ddp_practice_tpu.serve.faults import FaultPlan, FaultSpec

    out = tmp_path / "t.json"
    report = serve_bench(
        n_requests=12, rate_hz=200.0, max_slots=4, max_new_range=(2, 12),
        replicas=2, decode_burst=2,
        fault_plan=FaultPlan([FaultSpec(kind="crash", tick=3,
                                        replica=0, down_s=0.05)]),
        trace_out=str(out),
    )
    assert report["trace_out"] == str(out)
    trace = json.loads(out.read_text())
    assert validate(trace) == []
    router = report["router"]
    # the phase breakdown rides the report next to ttft/tpot
    for row in (report["continuous"], router):
        assert set(row["phases"]) == {"queue_s", "prefill_s",
                                      "decode_s", "stall_s"}
        assert row["phases"]["decode_s"]["p99"] > 0
    # the trace covers the ROUTER run: replica pids + router lane exist
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert {0, 1, ROUTER_PID} <= pids


@pytest.mark.slow
def test_train_trace_out_records_step_phases(tmp_path):
    """`cli.py ... --trace-out`: the training driver's host-side phases
    (data / dispatch / block / checkpoint) land in a validator-clean
    Chrome trace."""
    from ddp_practice_tpu import cli

    out = tmp_path / "train.json"
    assert cli.main([
        "--model", "lm_tiny", "--dataset", "synthetic_tokens",
        "--seq_len", "48", "-e", "1", "-b", "4", "--max_steps", "6",
        "--log_every", "3", "--ckpt_dir", str(tmp_path / "ck"),
        "--trace-out", str(out),
    ]) == 0
    trace = json.loads(out.read_text())
    assert validate(trace) == []
    spans = [e["name"] for e in trace["traceEvents"] if e["ph"] == "B"]
    assert spans.count("dispatch") == 6 and spans.count("data") == 6
    assert "block" in spans and "checkpoint" in spans
