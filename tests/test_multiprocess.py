"""Real multi-process distributed tests.

Every other test in this suite runs 1 process x 8 virtual devices; these
spawn 2 actual OS processes (2 CPU devices each, 4 global) that rendezvous
via `jax.distributed.initialize` and drive the code paths single-process
runs can never reach — the multi-HOST story (VERDICT weak #5): coordinator
rendezvous, `make_array_from_process_local_data` batches,
`assert_in_sync`'s allgather both passing and firing, process-0-only
checkpoint writes, and the per-process FSDP shard-file save (no
full-leaf gather — checkpoint/__init__.py).

The scenarios live in tests/mp_worker.py; this parent orchestrates
processes, asserts their exit status + final ALL_OK line, and then
restores the workers' multi-host FSDP checkpoint from a SINGLE process —
the cross-world-size restore contract.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed(tmp_path):
    nproc = 2
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    # children configure their own backend (cpu, 2 devices) — drop the
    # parent suite's 8-virtual-device forcing
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coord, str(nproc), str(i), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # collect what each worker said: communicate() after kill for the
        # hung ones; workers that already finished have closed pipes, so
        # their captured output comes from `outputs`
        for p in procs[len(outputs):]:
            try:
                out, _ = p.communicate(timeout=30)
            except (subprocess.SubprocessError, ValueError, OSError):
                out = "<no output captured>"
            outputs.append(out)
        pytest.fail("multi-process workers timed out\n" + "\n".join(outputs))
    if all(p.returncode == 77 for p in procs) and all(
            "MULTIPROCESS_CPU_UNSUPPORTED" in out for out in outputs):
        pytest.skip("this jax's CPU backend refuses multi-process "
                    "computations (worker capability probe)")
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}\n{out}"
        assert "ALL_OK" in out, f"worker {i} did not reach ALL_OK\n{out}"

    # single-host restore of the workers' MULTI-host FSDP checkpoint: the
    # shard files written by both processes reassemble in this one
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_practice_tpu import checkpoint as ckpt
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train import create_state, make_optimizer

    model = create_model("convnet")
    tx = make_optimizer(TrainConfig())
    abstract = jax.eval_shape(
        lambda r: create_state(
            model, tx, rng=r, sample_input=jnp.zeros((4, 28, 28, 1))
        ),
        jax.random.PRNGKey(0),
    )
    restored = ckpt.restore(str(tmp_path / "ck_fsdp"), abstract)
    expected = np.load(tmp_path / "ck_fsdp_expected.npy")
    with open(tmp_path / "ck_fsdp_leaf.json") as f:
        leaf_idx = json.load(f)["param_leaf_index"]
    got = np.asarray(jax.tree_util.tree_leaves(restored.params)[leaf_idx])
    np.testing.assert_allclose(got, expected)
