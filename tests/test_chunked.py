"""Chunked (K-steps-per-call) training: identical math to K single steps.

The scan-over-batches step exists purely to amortize host dispatch and H2D
latency (SURVEY §3.4's per-batch H2D loop); it must not change training
numerics, and the chunked prefetch must preserve batch order and handle
the sub-K epoch tail.
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.data.loader import prefetch_chunked
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step
from ddp_practice_tpu.train.steps import make_chunked_train_step


def _batch(n, seed):
    rng = np.random.default_rng(seed)
    return {
        "image": np.asarray(rng.uniform(size=(n, 28, 28, 1)), np.float32),
        "label": np.asarray(rng.integers(0, 10, n), np.int32),
        "weight": np.ones((n,), np.float32),
    }


def test_chunked_matches_sequential(devices):
    # SGD, not adam: the conv bias feeding BatchNorm has a ~zero gradient
    # (BN subtracts the mean), and adam normalizes that numerical noise up
    # to lr-scale updates whose sign flips with XLA op order — SGD keeps
    # updates proportional to gradients so the comparison is meaningful.
    mesh = build_mesh(MeshConfig(data=8))
    cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
    model = create_model("convnet")
    tx = make_optimizer(cfg)

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=jnp.zeros((1, 28, 28, 1)))

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, None)
    s_seq = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    s_chunk = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    bsh = batch_sharding(mesh)
    step = make_train_step(model, tx, mesh=mesh, state_shardings=shardings,
                           batch_shardings=bsh)
    chunk = make_chunked_train_step(
        model, tx, num_steps=4, mesh=mesh, state_shardings=shardings,
        batch_shardings=bsh,
    )

    batches = [_batch(8, seed=s) for s in range(4)]
    for b in batches:
        s_seq, m_seq = step(s_seq, {k: jnp.asarray(v) for k, v in b.items()})
    stacked = {
        k: jnp.asarray(np.stack([b[k] for b in batches])) for k in batches[0]
    }
    s_chunk, m_chunk = chunk(s_chunk, stacked)

    assert int(s_seq.step) == int(s_chunk.step) == 4
    np.testing.assert_allclose(
        float(m_seq["loss"]), float(m_chunk["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(s_seq.params), jax.tree.leaves(s_chunk.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.fast
def test_prefetch_chunked_order_and_tail(devices):
    """10 batches at K=4 -> two chunks (batches 0-3, 4-7) then two singles,
    in order, with values intact."""
    mesh = build_mesh(MeshConfig(data=8))
    bsh = batch_sharding(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = NamedSharding(mesh, P(None, *bsh.spec))
    host = [
        {"image": np.full((8, 2, 2, 1), i, np.float32),
         "label": np.full((8,), i, np.int32),
         "weight": np.ones((8,), np.float32)}
        for i in range(10)
    ]
    from ddp_practice_tpu.train.steps import stack_shardings

    assert stacked.spec == stack_shardings(bsh).spec  # helper agrees
    got = list(prefetch_chunked(iter(host), 4, bsh, stacked, size=2))
    tags = [t for t, _ in got]
    assert tags == ["chunk", "chunk", "single", "single"]
    first = np.asarray(got[0][1]["label"])
    assert first.shape == (4, 8)
    np.testing.assert_array_equal(first[:, 0], [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(got[2][1]["label"]), np.full(8, 8))


def test_chunked_eval_matches_per_batch(devices):
    """K-batches-per-call eval sums the same weighted counts as the
    per-batch step, including a weighted (padded) tail batch."""
    from ddp_practice_tpu.train.steps import make_chunked_eval_step, make_eval_step

    mesh = build_mesh(MeshConfig(data=8))
    cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
    model = create_model("convnet")
    tx = make_optimizer(cfg)

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=jnp.zeros((1, 28, 28, 1)))

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, None)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    bsh = batch_sharding(mesh)
    eval_step = make_eval_step(model, mesh=mesh, state_shardings=shardings,
                               batch_shardings=bsh)
    chunk_eval = make_chunked_eval_step(
        model, num_steps=4, mesh=mesh, state_shardings=shardings,
        batch_shardings=bsh,
    )

    batches = [_batch(8, seed=100 + s) for s in range(4)]
    batches[-1]["weight"][5:] = 0.0  # padded tail
    c_ref = t_ref = 0.0
    for b in batches:
        c, t = eval_step(state, {k: jnp.asarray(v) for k, v in b.items()})
        c_ref += float(c)
        t_ref += float(t)
    stacked = {
        k: jnp.asarray(np.stack([b[k] for b in batches])) for k in batches[0]
    }
    c_chunk, t_chunk = chunk_eval(state, stacked)
    assert t_ref == float(t_chunk) == 8 * 3 + 5
    np.testing.assert_allclose(c_ref, float(c_chunk), rtol=1e-6)


def test_trainer_chunked_eval_end_to_end(devices):
    """Trainer.evaluate with steps_per_call > 1 equals the per-batch path."""
    from ddp_practice_tpu.train.loop import Trainer

    base = dict(
        dataset="synthetic", epochs=1, batch_size=4, optimizer="adam",
        learning_rate=1e-3, log_every_steps=0, max_steps_per_epoch=4,
        mesh=MeshConfig(data=-1),
        data_placement="host",  # this test is about the host chunk path
    )
    # evaluate at identical (seeded) init: isolates the eval path — train
    # parity between chunked and single steps is proven separately above
    acc_chunk = Trainer(TrainConfig(steps_per_call=4, **base)).evaluate()
    acc_plain = Trainer(TrainConfig(**base)).evaluate()
    assert acc_chunk == acc_plain


def test_trainer_chunked_epoch(devices):
    """Trainer with steps_per_call > 1 trains the same number of steps."""
    from ddp_practice_tpu.train.loop import Trainer

    cfg = TrainConfig(
        dataset="synthetic", epochs=1, batch_size=4, optimizer="adam",
        learning_rate=1e-3, log_every_steps=0, steps_per_call=4,
        max_steps_per_epoch=12, mesh=MeshConfig(data=-1),
        data_placement="host",
    )
    tr = Trainer(cfg)
    tr.train_epoch(0)
    assert int(tr.state.step) == 12


def test_trainer_chunked_step_cap_not_divisible(devices):
    """max_steps_per_epoch not divisible by K: the cap is exact (the last
    chunk's tail runs as single steps), keeping resume-epoch math sound."""
    from ddp_practice_tpu.train.loop import Trainer

    cfg = TrainConfig(
        dataset="synthetic", epochs=1, batch_size=4, optimizer="adam",
        learning_rate=1e-3, log_every_steps=0, steps_per_call=4,
        max_steps_per_epoch=10, mesh=MeshConfig(data=-1),
        data_placement="host",
    )
    tr = Trainer(cfg)
    tr.train_epoch(0)
    assert int(tr.state.step) == 10
