"""Cross-process fleet e2e: REAL worker OS processes, REAL signals.

These are the teeth of the chaos story: everything the in-process
router proved against simulated crashes (serve/faults.py `crash`) is
re-proven here against actual process death — a SIGKILL mid-decode and
a SIGSTOP that leaves the process alive but silent. Every test spawns
real workers (jax import + engine warmup each, ~15 s/worker on this
one-core image), so everything here is `slow`; the signal-delivering
ones are `chaos` too. The host-pure halves of the same machinery live
in tests/test_worker_supervisor.py / test_worker_rpc.py.

Token-identity pins use one retry (`_tolerate_load_flake` idiom,
tests/test_serve_equivalence.py): this image's XLA CPU can flip a
near-tied greedy argmax between process runs under load — a real
divergence bug fails both attempts.
"""

import time

import numpy as np
import pytest

from ddp_practice_tpu.serve.engine import EngineConfig
from ddp_practice_tpu.serve.router import RouterConfig, make_router
from ddp_practice_tpu.serve.scheduler import (
    MonotonicClock,
    Request,
    Scheduler,
)
from ddp_practice_tpu.serve.supervisor import (
    RUNNING,
    SupervisorConfig,
    live_worker_pids,
    make_fleet_router,
)
from ddp_practice_tpu.serve.worker import WorkerSpec, build_model
from ddp_practice_tpu.utils.trace import ROUTER_PID, TraceRecorder

pytestmark = pytest.mark.slow

MODEL_KW = {"vocab_size": 64, "max_len": 64, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 64, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}
SPEC = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW, max_queue=64)
SUP_CFG = SupervisorConfig(restart_base_s=0.25, restart_budget=5,
                           ready_timeout_s=300.0)


def _trace(n=6, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = int(rng.integers(3, 9))
        out.append({
            "rid": i,
            "prompt": rng.integers(1, 64, plen).tolist(),
            "max_new_tokens": int(rng.integers(5, 9)),
        })
    return out


def _expected_tokens(trace):
    """Greedy oracle: the same model served by one in-process scheduler
    (token identity is slot/batch-composition independent — pinned
    since PR 1)."""
    model, params = build_model(MODEL_KW)
    eng_kw = dict(ENGINE_KW)
    eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
    from ddp_practice_tpu.serve.engine import SlotEngine

    engine = SlotEngine(model, params, EngineConfig(**eng_kw))
    sched = Scheduler(engine, max_queue=64)
    for t in trace:
        sched.submit(Request(**t))
    comps = sched.run_until_idle()
    assert all(c.status == "length" for c in comps)
    return {c.rid: list(c.tokens) for c in comps}, (model, params)


def _tolerate_load_flake(attempt, tries=2):
    for i in range(tries):
        try:
            return attempt()
        except AssertionError:
            if i == tries - 1:
                raise


# --------------------------------------------------- identity, no faults
def test_fleet_matches_inprocess_router_token_identity():
    """The RPC seam must be invisible to results: the same trace through
    2 worker PROCESSES and through the in-process 2-replica router
    yields identical greedy tokens, every request terminal."""

    def attempt():
        trace = _trace()
        expected, (model, params) = _expected_tokens(trace)
        router, sup, handles = make_fleet_router(
            SPEC, 2, sup_config=SUP_CFG
        )
        try:
            for t in trace:
                router.submit(Request(**t))
            comps = router.run_until_idle()
        finally:
            sup.stop()
        by_rid = {c.rid: c for c in comps}
        assert set(by_rid) == {t["rid"] for t in trace}
        assert all(c.status == "length" for c in by_rid.values())
        for rid, want in expected.items():
            assert by_rid[rid].tokens == want, f"rid {rid} diverged"
        # the work actually spread over both processes (least-loaded)
        dispatched = [len(h._stats) > 0 for h in handles]
        assert all(dispatched)
        # in-process router agreement rides the same oracle: both equal
        # `expected` => equal to each other
        eng_kw = dict(ENGINE_KW)
        eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
        inproc = make_router(model, params, 2, EngineConfig(**eng_kw),
                             clock=MonotonicClock(), max_queue=64,
                             config=RouterConfig())
        inproc.warmup()
        for t in trace:
            inproc.submit(Request(**t))
        in_comps = inproc.run_until_idle()
        assert {c.rid: c.tokens for c in in_comps
                if c.status == "length"} == expected

    _tolerate_load_flake(attempt)


# --------------------------------------------- THE acceptance: SIGKILL
@pytest.mark.chaos
def test_sigkill_mid_decode_failover_restart_and_readmission():
    """ISSUE 7 acceptance: SIGKILL one of two workers mid-decode —
    zero lost requests, survivor output greedy token-identical to the
    fault-free oracle with the ORIGINAL trace_id on the failover
    timeline, and the killed worker is respawned by the supervisor
    (backoff) and readmitted to dispatch only after a passing health
    probe."""

    def attempt():
        trace = _trace(n=6, seed=5)
        expected, _ = _expected_tokens(trace)
        tracer = TraceRecorder()
        router, sup, handles = make_fleet_router(
            SPEC, 2, sup_config=SUP_CFG, tracer=tracer
        )
        try:
            for t in trace:
                router.submit(Request(**t))
            # run until worker 0 is observably MID-DECODE: its salvage
            # point (tokens-so-far from the heartbeat poll) is non-empty
            deadline = time.monotonic() + 60
            while not any(st["tokens"]
                          for st in handles[0].outstanding.values()):
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            assert victim_rids, "nothing in flight on worker 0"
            pid0 = sup.worker(0).pid
            sup.kill(0, "SIGKILL")                 # the real thing
            comps = router.run_until_idle()
            # ---- zero lost, token-identical, original trace_id
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            for rid, want in expected.items():
                assert by_rid[rid].tokens == want, f"rid {rid} diverged"
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            events = tracer.to_chrome_trace()["traceEvents"]
            for rid in migrated:
                fo = [e for e in events
                      if e["ph"] == "i" and e["name"] == "failover"
                      and e["args"].get("trace_id") == f"r{rid}"]
                assert fo and all(e["pid"] == ROUTER_PID for e in fo)
            # ---- supervisor restart with backoff + health-probe gate
            deadline = time.monotonic() + 240
            while router.states()[0] != "healthy":
                assert time.monotonic() < deadline, (
                    f"worker 0 never readmitted: sup={sup.state(0)} "
                    f"router={router.states()}"
                )
                router.step()
                time.sleep(0.05)
            assert sup.restarts[0] >= 1
            assert sup.state(0) == RUNNING
            assert sup.worker(0).pid != pid0       # a NEW process
            # ---- readmitted to dispatch: healthy + least-loaded wins
            router.submit(Request(rid=999, prompt=[1, 2, 3],
                                  max_new_tokens=4))
            assert 999 in handles[0].outstanding   # it went to worker 0
            tail = router.run_until_idle()
            assert {c.rid: c.status for c in tail}[999] == "length"
        finally:
            sup.stop()

    _tolerate_load_flake(attempt)


# ------------------------------------------------------------- SIGSTOP
@pytest.mark.chaos
def test_sigstop_stale_heartbeat_put_down_and_failover():
    """SIGSTOP leaves the process alive by waitpid but silent on the
    wire: the handle's heartbeat budget must detect the zombie, SIGKILL
    it for real, fail its work over, and let the supervisor restart it
    — with every request still terminal."""
    trace = _trace(n=4, seed=9)
    router, sup, handles = make_fleet_router(
        SPEC, 2, sup_config=SUP_CFG, heartbeat_timeout_s=1.0
    )
    try:
        for t in trace:
            router.submit(Request(**t))
        deadline = time.monotonic() + 60
        while not handles[0].outstanding:
            assert time.monotonic() < deadline
            router.step()
        pid0 = sup.worker(0).pid
        sup.kill(0, "SIGSTOP")
        comps = router.run_until_idle()
        by_rid = {c.rid: c for c in comps}
        assert set(by_rid) == {t["rid"] for t in trace}
        assert all(c.status == "length" for c in by_rid.values())
        # the zombie was put down with a REAL kill: the pid is gone
        # (reaped by the supervisor), not just suspended
        deadline = time.monotonic() + 30
        while sup.workers[0] is not None \
                and getattr(sup.workers[0], "pid", None) == pid0:
            assert time.monotonic() < deadline
            sup.poll()
            time.sleep(0.05)
        assert pid0 not in live_worker_pids()
    finally:
        sup.stop()
    assert live_worker_pids() == []   # the reaper fixture's invariant,
    #                                   asserted eagerly per test too
