"""Real-format data path, end to end (round-5 verdict item 5).

The committed fixtures in tests/data/ are REAL on-disk formats — gzip
IDX files byte-identical in structure to the MNIST distribution, and
CIFAR-10 python pickle batches — with small synthetic (separable)
pixels, so the full real-data path (`load_mnist`/`_load_cifar10` ->
DataLoader -> Trainer) runs and LEARNS in CI without network egress.
With the genuine archives ingested (`python -m
ddp_practice_tpu.data.ingest`), the identical path reproduces the
reference's 91.55%-in-3-epochs contract (PARITY.md "with real files").
"""

import os
import shutil

import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data")
MINI_MNIST = os.path.join(FIXTURES, "mini_mnist")
MINI_CIFAR = os.path.join(FIXTURES, "mini_cifar")


@pytest.mark.fast
def test_mini_mnist_loads_as_real_idx():
    from ddp_practice_tpu.data.datasets import load_dataset, load_mnist

    train = load_mnist(MINI_MNIST, "train")
    assert train is not None and train.name == "mnist-train"
    assert train.images.shape == (256, 28, 28, 1)
    assert train.images.dtype == np.uint8
    # the registry resolves to the REAL loader, not the synthetic stand-in
    ds = load_dataset("mnist", MINI_MNIST, "test", seed=0)
    assert ds.name == "mnist-test" and len(ds) == 64


def test_mini_cifar_loads_as_real_batches():
    from ddp_practice_tpu.data.datasets import _load_cifar10

    train = _load_cifar10(MINI_CIFAR, "train")
    test = _load_cifar10(MINI_CIFAR, "test")
    assert train.images.shape == (250, 32, 32, 3)
    assert test.images.shape == (50, 32, 32, 3)
    assert train.images.dtype == np.uint8


def test_mnist_idx_trains_end_to_end():
    """fit() on the committed IDX files: the real-format loader feeds
    the full Trainer and the model learns (the pixels are separable;
    chance is 10%)."""
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.train.loop import fit

    summary = fit(TrainConfig(
        model="convnet", dataset="mnist", data_dir=MINI_MNIST,
        epochs=4, batch_size=4, optimizer="adam", learning_rate=3e-3,
        log_every_steps=0, compilation_cache="off",
    ))
    assert summary["accuracy"] > 0.5, summary


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_cifar_batches_train_end_to_end():
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.train.loop import fit

    summary = fit(TrainConfig(
        model="convnet", dataset="cifar10", data_dir=MINI_CIFAR,
        epochs=4, batch_size=5, optimizer="adam", learning_rate=3e-3,
        log_every_steps=0, compilation_cache="off",
    ))
    assert summary["accuracy"] > 0.5, summary


def test_ingest_places_and_structurally_verifies(tmp_path):
    """The ingest tool finds IDX files under a torchvision-style tree,
    checks their structure, and places them where the loader looks.
    (Checksums apply to the canonical archives; the fixture uses
    --no-verify exactly as its docstring prescribes.)"""
    from ddp_practice_tpu.data.datasets import load_mnist
    from ddp_practice_tpu.data.ingest import ingest_mnist

    src = tmp_path / "torch_data" / "MNIST" / "raw"
    src.parent.mkdir(parents=True)
    shutil.copytree(MINI_MNIST, src)
    out = tmp_path / "data"
    rc = ingest_mnist(str(tmp_path / "torch_data"), str(out), verify=False)
    assert rc == 0
    assert load_mnist(str(out), "train") is not None


def test_ingest_rejects_wrong_checksum(tmp_path):
    """A file with the canonical name but the wrong bytes must fail
    loudly under verification, never train silently."""
    from ddp_practice_tpu.data.ingest import ingest_mnist

    src = tmp_path / "src"
    src.mkdir()
    shutil.copy(
        os.path.join(MINI_MNIST, "train-images-idx3-ubyte.gz"),
        src / "train-images-idx3-ubyte.gz",
    )
    with pytest.raises(SystemExit, match="checksum mismatch"):
        ingest_mnist(str(src), str(tmp_path / "out"), verify=True)


def test_ingest_cifar_tree_structural_check(tmp_path):
    """A pre-extracted CIFAR tree is structurally verified (batch count,
    3072-wide uint8 rows, label count) before being placed; a truncated
    batch fails loudly."""
    from ddp_practice_tpu.data.ingest import ingest_cifar10

    # the good fixture passes
    out = tmp_path / "data"
    rc = ingest_cifar10(MINI_CIFAR, str(out), verify=True)
    assert rc == 0
    assert (out / "cifar-10-batches-py" / "data_batch_1").exists()

    # a corrupted copy fails
    import pickle

    bad_src = tmp_path / "bad"
    shutil.copytree(
        os.path.join(MINI_CIFAR, "cifar-10-batches-py"),
        bad_src / "cifar-10-batches-py",
    )
    with open(bad_src / "cifar-10-batches-py" / "data_batch_3", "wb") as f:
        pickle.dump({b"data": np.zeros((5, 7), np.uint8),
                     b"labels": [0] * 5}, f)
    with pytest.raises(SystemExit, match="not a CIFAR batch"):
        ingest_cifar10(str(bad_src), str(tmp_path / "out2"), verify=True)
