"""Trace-plane sampling: coherent head decisions, tail keep, OTLP.

Host-pure halves first — the crc32 head decision (deterministic across
REAL OS processes, not just within one interpreter), the TraceSampler
keep-rules, the TraceRecorder staging/promotion state machine under a
FakeClock (every tail keep-rule pinned: error, shed, timeout, slow,
preempt, failover, retry, resumed), exemplar gating (histograms and
/flight must only cite KEPT trace_ids), collector coherence (a worker
that streamed a span has decided KEEP — the router honors it), and the
OTLP-JSON export against tools/check_otlp.py.

Then the integration tiers: a real SlotEngine + Scheduler run at a 10%
head rate (the in-process half of the coherence contract), and THE
acceptance e2e (slow+chaos): a 2-worker fleet at a 1% head rate,
worker 0 SIGKILLed mid-decode — every failover-affected request must
surface in the KEPT timeline under its ORIGINAL trace_id while the
clean 99% stay suppressed, the merged trace validates fleet-clean, and
the OTLP export round-trips against the Chrome export.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from ddp_practice_tpu.serve.scheduler import Completion
from ddp_practice_tpu.utils.metrics import MetricsRegistry
from ddp_practice_tpu.utils.trace import (
    KEEP_MARKERS,
    TraceCollector,
    TraceRecorder,
    TraceSampler,
    head_keep,
)
from tools.check_otlp import crosscheck_chrome, validate_otlp
from tools.check_traces import validate, validate_fleet


class _Clk:
    """Minimal settable clock for recorder-level tests."""

    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t


def _completion(rid=1, *, status="eos", trace_id=None, sampled=True,
                ttft=0.05, tpot=0.01):
    return Completion(
        rid=rid, tokens=[1, 2, 3], status=status, arrival=0.0,
        finish=1.0, ttft=ttft, tpot=tpot,
        trace_id=trace_id or f"r{rid}", trace_sampled=sampled,
    )


# ------------------------------------------------- head decision (host-pure)
def test_head_keep_deterministic_and_rate_shaped():
    for tid in ("r0", "r64", "r123456", "weird:id"):
        assert head_keep(tid, 1.0) is True
        assert head_keep(tid, 0.0) is False
        # determinism: same inputs, same answer, every call
        assert head_keep(tid, 0.3) == head_keep(tid, 0.3)
        # monotone in rate: once kept at r, kept at every higher rate
        if head_keep(tid, 0.01):
            assert head_keep(tid, 0.5)
    # the empirical rate lands near the nominal one (crc32 uniformity)
    n = sum(head_keep(f"r{i}", 0.1) for i in range(5000))
    assert 350 < n < 650


def test_head_keep_agrees_across_real_os_processes():
    """The Dapper coherence requirement that Python's salted hash()
    breaks: a SEPARATE interpreter must reach the identical decisions.
    trace.py's module-level imports are stdlib-only, so the child loads
    it standalone (no jax import) and stays fast."""
    from ddp_practice_tpu.utils import trace as trace_mod

    ids = [f"r{i}" for i in range(300)]
    prog = (
        "import importlib.util, json, sys\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'t', {trace_mod.__file__!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        f"ids = {ids!r}\n"
        "print(json.dumps([m.head_keep(t, 0.01) for t in ids]))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=60, check=True,
    )
    remote = json.loads(out.stdout)
    local = [head_keep(t, 0.01) for t in ids]
    assert remote == local
    assert any(local), "0/300 sampled at 1% — hash degenerate?"


def test_sampler_keep_reasons():
    s = TraceSampler(0.0, keep_slow_s=2.0)
    assert s.keep_reason(status="eos", latency_s=0.5) is None
    assert s.keep_reason(status="length", latency_s=0.5) is None
    for bad in ("error", "shed", "timeout", "rejected"):
        assert s.keep_reason(status=bad) == bad
    # failover outranks retry (one request can carry both)
    assert s.keep_reason(status="eos", retries=1, failovers=2) \
        == "failover"
    assert s.keep_reason(status="eos", retries=1) == "retry"
    assert s.keep_reason(status="eos", latency_s=2.5) == "slow"
    assert TraceSampler(0.0).keep_reason(status="eos",
                                         latency_s=9e9) is None
    with pytest.raises(ValueError):
        TraceSampler(0.5, stage_limit=0)


# --------------------------------------- staging state machine (FakeClock)
def _rec(rate=0.0, **kw):
    clk = _Clk()
    r = TraceRecorder(clock=clk)
    r.set_sampler(TraceSampler(rate, **kw))
    return r, clk


def _events(r):
    # begin-phase events only: spans/asyncs export as matched B/E (b/e)
    # pairs, so counting every phase would double each record
    return [e for e in r.to_chrome_trace()["traceEvents"]
            if e.get("ph") in ("B", "b", "i", "X")]


def test_clean_unsampled_trace_is_suppressed():
    r, clk = _rec(0.0)
    assert r.begin_trace("rA") is False
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    r.record_async("request", 0.0, 0.2, trace_id="rA", pid=0)
    assert _events(r) == []          # staged, not in the timeline
    assert r.finish_trace("rA", status="eos", latency_s=0.2) is False
    assert _events(r) == []
    assert r.traces_suppressed == 1 and r.spans_suppressed == 2
    assert r.trace_recorded("rA") is False


@pytest.mark.parametrize("status", ["error", "shed", "timeout",
                                    "rejected"])
def test_bad_status_tail_keeps_staged_spans(status):
    r, clk = _rec(0.0)
    r.begin_trace("rA")
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    clk.t = 0.2
    assert r.finish_trace("rA", status=status, latency_s=0.2) is True
    names = [e["name"] for e in _events(r)]
    assert "prefill" in names        # staged span flushed on promotion
    assert r.traces_kept == 1 and r.kept_reasons == {status: 1}
    assert r.trace_recorded("rA") is True


def test_slow_latency_tail_keeps():
    r, _ = _rec(0.0, keep_slow_s=1.0)
    r.begin_trace("rA")
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    assert r.finish_trace("rA", status="eos", latency_s=3.0) is True
    assert r.kept_reasons == {"slow": 1}
    r.begin_trace("rB")
    assert r.finish_trace("rB", status="eos", latency_s=0.5) is False


def test_retry_and_failover_counts_tail_keep():
    r, _ = _rec(0.0)
    r.begin_trace("rA")
    assert r.finish_trace("rA", status="eos", latency_s=0.1,
                          failovers=1) is True
    r.begin_trace("rB")
    assert r.finish_trace("rB", status="eos", latency_s=0.1,
                          retries=2) is True
    assert r.kept_reasons == {"failover": 1, "retry": 1}


@pytest.mark.parametrize("marker", ["preempted", "preempt", "failover",
                                    "retry", "resumed"])
def test_marker_instants_promote_on_the_spot(marker):
    """Anomaly markers must promote IMMEDIATELY (not at finish): a
    SIGKILL after the marker must not take the staged spans with it."""
    assert marker in KEEP_MARKERS
    r, _ = _rec(0.0)
    r.begin_trace("rA")
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    assert _events(r) == []
    r.record_instant(marker, 0.15, trace_id="rA", pid=0)
    names = [e["name"] for e in _events(r)]
    assert "prefill" in names and marker in names
    assert r.kept_reasons == {marker: 1}
    # post-promotion records flow directly
    r.record_span("decode_burst", 0.2, 0.3, trace_id="rA", pid=0)
    assert "decode_burst" in [e["name"] for e in _events(r)]
    # ...and the later finish does not double-count the keep
    assert r.finish_trace("rA", status="error", latency_s=1.0) is True
    assert r.traces_kept == 1


def test_note_keep_promotes_and_is_idempotent():
    r, _ = _rec(0.0)
    r.begin_trace("rA")
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    r.note_keep("rA", "resumed")
    r.note_keep("rA", "resumed")     # second call: no-op
    assert r.kept_reasons == {"resumed": 1}
    assert r.trace_recorded("rA") is True
    # unknown / head-sampled ids are no-ops too
    r.note_keep("never-begun", "resumed")
    assert r.traces_kept == 1


def test_stage_limit_bounds_memory_and_counts_overflow():
    r, _ = _rec(0.0, stage_limit=4)
    r.begin_trace("rA")
    for i in range(10):
        r.record_span("s", i * 0.1, i * 0.1 + 0.05, trace_id="rA",
                      pid=0, tid=1)
    assert r.finish_trace("rA", status="eos", latency_s=1.0) is False
    # 4 staged + 6 overflowed, all suppressed
    assert r.spans_suppressed == 10


def test_begin_idempotent_finish_memoized():
    """Scheduler and router share one in-process recorder: both begin
    and both finish every request — the first verdict must stick."""
    r, _ = _rec(0.0)
    first = r.begin_trace("rA")
    assert r.begin_trace("rA") == first
    assert r.finish_trace("rA", status="error", latency_s=0.1) is True
    # second finish (clean status) must NOT flip the recorded verdict
    assert r.finish_trace("rA", status="eos", latency_s=0.1) is True
    assert r.traces_kept == 1 and r.traces_suppressed == 0


def test_upstream_decision_overrides_local_hash():
    """The RPC seam: the router's verdict rides the submit frame and a
    worker must honor it even when its own hash would disagree."""
    r, _ = _rec(0.0)                  # local hash says: stage everything
    assert r.begin_trace("rA", sampled=True) is True
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    assert [e["name"] for e in _events(r)] == ["prefill"]
    r2, _ = _rec(1.0)                 # local hash says: sample everything
    assert r2.begin_trace("rB", sampled=False) is False
    r2.record_span("prefill", 0.0, 0.1, trace_id="rB", pid=0, tid=1)
    assert _events(r2) == []


def test_coherence_two_recorders_same_decisions():
    """Router-side and worker-side recorders with the same rate reach
    identical head decisions for identical trace_ids — the in-process
    statement of the cross-process contract."""
    ra, _ = _rec(0.07)
    rb, _ = _rec(0.07)
    ids = [f"r{i}" for i in range(500)]
    da = [ra.begin_trace(t) for t in ids]
    db = [rb.begin_trace(t) for t in ids]
    assert da == db == [head_keep(t, 0.07) for t in ids]
    assert any(da) and not all(da)


def test_engine_lane_spans_gate_on_flowing_sampled_traces():
    """decode_burst spans carry no trace_id (shared lane). With
    `sampled_only` they record only while a sampled/kept request is in
    flight — the residual-cost rule that gets a 1% plane to >=95%
    span reduction instead of ~86%."""
    r, _ = _rec(0.0)
    with r.span("decode_burst", pid=0, tid=0, sampled_only=True):
        pass
    assert _events(r) == []          # nothing flowing: suppressed
    assert r.spans_suppressed == 1
    r.begin_trace("rA", sampled=True)
    with r.span("decode_burst", pid=0, tid=0, sampled_only=True):
        pass
    assert [e["name"] for e in _events(r)] == ["decode_burst"]
    r.finish_trace("rA", status="eos", latency_s=0.1)
    with r.span("decode_burst", pid=0, tid=0, sampled_only=True):
        pass
    assert len(_events(r)) == 1      # flow ended: gated again
    # without the flag, shared-lane spans always record
    with r.span("decode_burst", pid=0, tid=0):
        pass
    assert len(_events(r)) == 2


def test_sampling_counters_and_metadata():
    reg = MetricsRegistry()
    clk = _Clk()
    r = TraceRecorder(clock=clk)
    r.set_sampler(TraceSampler(0.0, keep_slow_s=5.0), registry=reg)
    r.begin_trace("rA", sampled=True)
    r.record_span("prefill", 0.0, 0.1, trace_id="rA", pid=0, tid=1)
    r.begin_trace("rB")
    r.record_span("prefill", 0.0, 0.1, trace_id="rB", pid=0, tid=1)
    r.finish_trace("rA", status="eos", latency_s=0.1)
    r.finish_trace("rB", status="error", latency_s=0.1)
    r.begin_trace("rC")
    r.record_span("prefill", 0.0, 0.1, trace_id="rC", pid=0, tid=1)
    r.finish_trace("rC", status="eos", latency_s=0.1)
    snap = reg.snapshot()
    assert snap["trace_spans_sampled_total"] == 1
    assert snap["trace_spans_kept_total"] == 1
    assert snap["trace_spans_suppressed_total"] == 1
    assert snap["trace_traces_kept_total{reason=error}"] == 1
    meta = r.sampling_meta()
    assert meta["traces_sampled"] == 1 and meta["traces_kept"] == 1
    assert meta["traces_suppressed"] == 1
    assert meta["kept_reasons"] == {"error": 1}
    # the chrome export carries the sampling header
    md = r.to_chrome_trace()["metadata"]
    assert md["sampling"]["head_rate"] == 0.0
    # ...and a sampler-less recorder carries none
    assert TraceRecorder().sampling_meta() is None


def test_collector_ingest_honors_worker_keep_verdict():
    """A worker only streams spans for traces IT kept; if the router
    staged its own records for that trace, the frame must promote them
    — one request, one verdict, fleet-wide."""
    clk = _Clk()
    rec = TraceRecorder(clock=clk)
    rec.set_sampler(TraceSampler(0.0))
    col = TraceCollector(rec)
    rec.begin_trace("r7")            # router stages (unsampled locally)
    rec.record_instant("dispatch", 0.01, trace_id="r7", pid=-1)
    assert _events(rec) == []
    col.ingest(0, {"seq": 0, "events": [
        {"kind": "span", "name": "prefill", "t0": 0.02, "t1": 0.05,
         "trace_id": "r7", "pid": 0, "tid": 1},
    ]})
    names = {e["name"] for e in _events(rec)}
    assert {"dispatch", "prefill"} <= names
    assert rec.kept_reasons == {"remote": 1}


# ------------------------------------------------------- exemplar gating
def test_serve_metrics_exemplars_cite_only_kept_traces():
    from ddp_practice_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.on_complete(_completion(1, sampled=False), None)
    assert m.ttft._exemplars is None       # suppressed: latency counts,
    assert m.ttft.count == 1               # exemplar does not
    m.on_complete(_completion(2, sampled=True), None)
    cited = {e[0] for e in m.ttft._exemplars if e is not None}
    assert cited == {"r2"}


def test_router_metrics_exemplars_cite_only_kept_traces():
    from ddp_practice_tpu.serve.metrics import RouterMetrics

    m = RouterMetrics()
    m.on_finalize(_completion(1, sampled=False))
    assert m.ttft._exemplars is None
    m.on_finalize(_completion(2, sampled=True))
    cited = {e[0] for e in m.ttft._exemplars if e is not None}
    assert cited == {"r2"}


def test_flight_stats_p99_exemplar_gated_by_sampling():
    from ddp_practice_tpu.utils.telemetry import FlightStats

    fs = FlightStats()
    for i in range(20):
        fs.on_completion(_completion(i, sampled=False, ttft=float(i)))
    rep = fs.report()
    assert rep["ttft_s"]["p99"] > 0        # samples still counted
    assert "exemplars" not in rep          # but nothing citable
    fs2 = FlightStats()
    for i in range(20):
        fs2.on_completion(_completion(i, sampled=True, ttft=float(i)))
    ex = fs2.report()["exemplars"]["ttft_p99"]
    assert ex is not None and ex["trace_id"].startswith("r")


# ------------------------------------------------------------ OTLP export
def _recorded_trace():
    clk = _Clk()
    r = TraceRecorder(clock=clk)
    r.set_process_name(0, "replica0")
    r.set_process_name(-1, "router")
    for rid in (1, 2):
        t = f"r{rid}"
        r.record_async("queued", 0.0, 0.01 * rid, trace_id=t, pid=0)
        r.record_span("prefill", 0.01 * rid, 0.02 * rid, trace_id=t,
                      pid=0, tid=1)
        r.record_instant("dispatch", 0.005, trace_id=t, pid=-1,
                         attrs={"replica": 0})
        r.record_async("request", 0.0, 0.1 * rid, trace_id=t, pid=0,
                       attrs={"status": "eos" if rid == 1 else "error"})
    r.record_span("decode_burst", 0.05, 0.06, pid=0, tid=0)  # no tid
    return r


def test_otlp_shape_parent_linkage_and_roundtrip():
    r = _recorded_trace()
    otlp = r.to_otlp()
    assert validate_otlp(otlp) == []
    spans = [s for rs in otlp["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    # one span per trace-tagged record; infrastructure stays chrome-only
    assert len(spans) == 8
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["traceId"], []).append(s)
    assert len(by_trace) == 2
    for tid, group in by_trace.items():
        roots = [s for s in group if "parentSpanId" not in s]
        assert [s["name"] for s in roots] == ["request"]
        root_sid = roots[0]["spanId"]
        for s in group:
            if s is not roots[0]:
                assert s["parentSpanId"] == root_sid
    # status mapping: clean -> OK, error -> ERROR with message
    stats = {s["attributes"][0]["value"]["stringValue"]:
             s.get("status") for s in spans if s["name"] == "request"}
    assert stats["r1"] == {"code": 1}
    assert stats["r2"] == {"code": 2, "message": "error"}
    # round-trip against the chrome export from the SAME recorder
    assert crosscheck_chrome(otlp, r.to_chrome_trace()) == []


def test_otlp_validator_rejects_corruption():
    r = _recorded_trace()
    good = r.to_otlp()

    def spans_of(o):
        return o["resourceSpans"][0]["scopeSpans"][0]["spans"]

    bad = json.loads(json.dumps(good))
    spans_of(bad)[0]["traceId"] = "xyz"
    assert any("traceId" in e for e in validate_otlp(bad))
    bad = json.loads(json.dumps(good))
    spans_of(bad)[1]["parentSpanId"] = "deadbeefdeadbeef"
    assert any("orphaned" in e for e in validate_otlp(bad))
    bad = json.loads(json.dumps(good))
    spans_of(bad)[0]["startTimeUnixNano"] = 123  # int, not str
    assert any("digit-string" in e for e in validate_otlp(bad))
    bad = json.loads(json.dumps(good))
    spans_of(bad)[1]["spanId"] = spans_of(bad)[0]["spanId"]
    assert any("duplicate spanId" in e for e in validate_otlp(bad))
    # round-trip mismatch: drop one trace from the OTLP side
    bad = json.loads(json.dumps(good))
    tid0 = spans_of(bad)[0]["traceId"]
    spans_of(bad)[:] = [s for s in spans_of(bad)
                        if s["traceId"] != tid0]
    assert any("round-trip" in e
               for e in crosscheck_chrome(bad, r.to_chrome_trace()))


def test_otlp_export_of_unsampled_run_is_small_and_valid():
    r, _ = _rec(0.0)
    r.set_process_name(0, "replica0")
    for rid in range(50):
        t = f"r{rid}"
        r.begin_trace(t)
        r.record_span("prefill", 0.0, 0.1, trace_id=t, pid=0, tid=1)
        r.finish_trace(t, status="error" if rid == 7 else "eos",
                       latency_s=0.1)
    otlp = r.to_otlp()
    assert validate_otlp(otlp) == []
    spans = [s for rs in otlp["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert len(spans) == 1           # only the kept (error) trace
    # resource attributes carry the sampling header
    res = {kv["key"]: kv["value"]
           for kv in otlp["resourceSpans"][0]["resource"]["attributes"]}
    assert res["ddp.sampling.head_rate"] == {"doubleValue": 0.0}
    assert res["ddp.sampling.traces_suppressed"] == {"intValue": "49"}


# ------------------------------------------- scheduler integration (real)
VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def test_scheduler_head_samples_end_to_end(devices, lm):
    """30 requests through a REAL SlotEngine at a 10% head rate: the
    completions' trace_sampled bits match head_keep exactly, no
    unsampled trace_id leaks into the timeline, and the OTLP export
    carries exactly the sampled population."""
    from ddp_practice_tpu.serve import (
        EngineConfig,
        FakeClock,
        Request,
        Scheduler,
        ServeMetrics,
        SlotEngine,
    )

    model, params = lm
    engine = SlotEngine(model, params, EngineConfig(
        max_slots=3, max_len=96, prompt_buckets=(8,), eos_id=-1,
    ))
    tracer = TraceRecorder()
    tracer.set_sampler(TraceSampler(0.10))
    engine.tracer = tracer
    sched = Scheduler(engine, clock=FakeClock(step_s=0.01),
                      max_queue=64, metrics=ServeMetrics(),
                      tracer=tracer)
    rng = np.random.default_rng(7)
    for i in range(30):
        plen = int(rng.integers(1, 9))
        sched.submit(Request(
            rid=i, prompt=rng.integers(0, VOCAB, plen).tolist(),
            max_new_tokens=int(rng.integers(2, 6)),
        ))
    comps = sched.run_until_idle()
    assert len(comps) == 30
    expect = [i for i in range(30) if head_keep(f"r{i}", 0.10)]
    assert sorted(c.rid for c in comps if c.trace_sampled) == expect
    assert expect, "seed produced no sampled rids — pick another"
    chrome = tracer.to_chrome_trace()
    assert validate(chrome) == []
    leaked = set()
    for e in chrome["traceEvents"]:
        t = (e.get("args") or {}).get("trace_id") or e.get("id")
        if isinstance(t, str) and t.startswith("r") \
                and int(t[1:]) not in expect:
            leaked.add(t)
    assert not leaked
    otlp = tracer.to_otlp()
    assert validate_otlp(otlp) == []
    assert crosscheck_chrome(otlp, chrome) == []
    meta = tracer.sampling_meta()
    assert meta["traces_sampled"] == len(expect)
    assert meta["traces_suppressed"] == 30 - len(expect)


# ---------------------------------------------- fleet acceptance (e2e)
MODEL_KW = {"vocab_size": 64, "max_len": 128, "hidden_dim": 64,
            "depth": 2, "num_heads": 4, "mlp_dim": 128,
            "pos_emb": "rope"}
ENGINE_KW = {"max_slots": 2, "max_len": 128, "prompt_buckets": [8, 16],
             "temperature": 0.0, "decode_burst": 4, "eos_id": None}


def _fleet_trace(n=6, seed=5):
    rng = np.random.default_rng(seed)
    return [{
        "rid": i,
        "prompt": rng.integers(1, 64, int(rng.integers(3, 9))).tolist(),
        "max_new_tokens": int(rng.integers(80, 101)),
    } for i in range(n)]


@pytest.mark.slow
@pytest.mark.chaos
def test_sampled_fleet_keeps_every_fault_affected_request(tmp_path):
    """ISSUE 11 acceptance: a 2-worker fleet at a 1% head rate,
    worker 0 SIGKILLed mid-decode. Every failover-affected request must
    be present in the KEPT timeline under its ORIGINAL trace_id (the
    tail keep promoted it; the clean rest stayed suppressed), the
    merged trace validates fleet-clean, and the OTLP export of the run
    round-trips against the Chrome export via tools/check_otlp.py."""
    from ddp_practice_tpu.serve.scheduler import Request
    from ddp_practice_tpu.serve.supervisor import (
        SupervisorConfig,
        make_fleet_router,
    )
    from ddp_practice_tpu.serve.worker import WorkerSpec
    from tools import check_otlp, check_traces

    def attempt():
        trace = _fleet_trace(n=6, seed=5)
        # every rid in this trace is head-UNSAMPLED at 1% (pinned, so
        # the keeps below are provably tail-based, not hash luck)
        assert not any(head_keep(f"r{t['rid']}", 0.01) for t in trace)
        tracer = TraceRecorder()
        spec = WorkerSpec(model=MODEL_KW, engine=ENGINE_KW,
                          max_queue=64, trace=True, trace_sample=0.01)
        router, sup, handles = make_fleet_router(
            spec, 2, tracer=tracer,
            sup_config=SupervisorConfig(restart_base_s=0.25,
                                        restart_budget=5,
                                        ready_timeout_s=300.0),
        )
        try:
            assert tracer.sampler is not None   # fleet builder wired it
            for t in trace:
                router.submit(Request(**t))

            def victim_busy():
                w = sup.worker(0)
                if w is None:
                    return False
                try:
                    st = w.client.call("ping", timeout_s=2.0)["stats"]
                    return st["active"] > 0
                except Exception:
                    return False

            deadline = time.monotonic() + 60
            while not victim_busy():
                assert time.monotonic() < deadline, "never saw decode"
                router.step()
            victim_rids = sorted(handles[0].outstanding)
            sup.kill(0, "SIGKILL")
            comps = router.run_until_idle()
            by_rid = {c.rid: c for c in comps}
            assert set(by_rid) == {t["rid"] for t in trace}
            assert all(c.status == "length" for c in by_rid.values())
            migrated = [rid for rid in victim_rids
                        if by_rid[rid].flight["failovers"] >= 1]
            assert migrated, "the kill migrated nothing"
            # ---- exemplar gate rode the completions: migrated kept,
            # untouched-clean suppressed
            for rid in migrated:
                assert by_rid[rid].trace_sampled, f"r{rid} not kept"
            clean = [rid for rid, c in by_rid.items()
                     if c.flight["failovers"] == 0
                     and c.flight["retries"] == 0]
            assert clean, "every request was fault-affected?"
            for rid in clean:
                assert not by_rid[rid].trace_sampled
            # ---- the kept timeline: every migrated request present
            # under its ORIGINAL trace_id; validator-clean fleet mode
            chrome = tracer.to_chrome_trace()
            assert validate(chrome) == []
            assert validate_fleet(chrome) == []
            ids_in_trace = set()
            for e in chrome["traceEvents"]:
                a = e.get("args") or {}
                if "trace_id" in a:
                    ids_in_trace.add(a["trace_id"])
                if e.get("id") is not None:
                    ids_in_trace.add(e["id"])
            for rid in migrated:
                assert f"r{rid}" in ids_in_trace
            # survivor-side spans for some migrated request (the
            # failover-forced sampled bit crossed the RPC seam)
            assert any(
                e.get("pid") == 1 and (
                    (e.get("args") or {}).get("trace_id")
                    in {f"r{rid}" for rid in migrated}
                    or e.get("id") in {f"r{rid}" for rid in migrated})
                for e in chrome["traceEvents"] if e.get("ph") != "M")
            for rid in clean:
                assert f"r{rid}" not in ids_in_trace
            # ---- sampling header says what happened
            sm = chrome["metadata"]["sampling"]
            assert sm["head_rate"] == 0.01
            assert sm["traces_kept"] >= len(migrated)
            # ---- CLI validators agree, artifacts on disk
            cpath, opath = tmp_path / "c.json", tmp_path / "o.json"
            tracer.save(str(cpath))
            tracer.save_otlp(str(opath))
            assert check_traces.main(["--fleet", str(cpath)]) == 0
            assert check_otlp.main(
                [str(opath), "--chrome", str(cpath)]) == 0
        finally:
            sup.stop()

    for i in range(2):   # one retry for the documented XLA-CPU near-tie
        try:
            return attempt()
        except AssertionError:
            if i == 1:
                raise
