"""Model tests: forward shapes/dtypes, precision policy, parameter shapes.

The ConvNet contract comes from the reference architecture
(origin_main.py:12-24): conv5x5(1->16) -> BN -> relu -> pool, conv5x5(16->32)
-> BN -> relu -> pool, dense(7*7*32 -> 10).
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import PrecisionPolicy
from ddp_practice_tpu.models import create_model


def _init_and_apply(model, x, train=False):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if train and "batch_stats" in variables:
        out, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
        return variables, out
    return variables, model.apply(variables, x, train=train)


@pytest.mark.fast
def test_convnet_shapes_match_reference():
    model = create_model("convnet")
    x = jnp.zeros((2, 28, 28, 1))
    variables, logits = _init_and_apply(model, x)
    assert logits.shape == (2, 10)
    params = variables["params"]
    # conv 5x5, 1->16 then 16->32 (origin_main.py:13-22), dense 7*7*32 -> 10
    assert params["Conv_0"]["kernel"].shape == (5, 5, 1, 16)
    assert params["Conv_1"]["kernel"].shape == (5, 5, 16, 32)
    assert params["Dense_0"]["kernel"].shape == (7 * 7 * 32, 10)
    assert "batch_stats" in variables  # BatchNorm present


def test_convnet_bf16_policy_fp32_logits():
    model = create_model("convnet", policy=PrecisionPolicy.bf16())
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    variables, logits = _init_and_apply(model, x)
    assert logits.dtype == jnp.float32      # loss math stays fp32
    # params stay fp32 (master weights)
    leaf = variables["params"]["Conv_0"]["kernel"]
    assert leaf.dtype == jnp.float32


def test_resnet18_forward():
    model = create_model("resnet18")
    x = jnp.zeros((2, 32, 32, 3))
    _, logits = _init_and_apply(model, x)
    assert logits.shape == (2, 10)


def test_resnet50_forward():
    model = create_model("resnet50")
    x = jnp.zeros((1, 64, 64, 3))
    _, logits = _init_and_apply(model, x)
    assert logits.shape == (1, 10)


@pytest.mark.fast
def test_vit_tiny_forward():
    model = create_model("vit_tiny", depth=2)
    x = jnp.zeros((2, 32, 32, 3))
    _, logits = _init_and_apply(model, x)
    assert logits.shape == (2, 10)


def test_train_eval_mode_differ_through_bn():
    """BN uses batch stats in train, running stats in eval — the
    model.train()/model.eval() split of ddp_main.py:84,98."""
    model = create_model("convnet")
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 28, 28, 1)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out_train, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
    out_eval = model.apply(variables, x, train=False)
    assert not np.allclose(np.asarray(out_train), np.asarray(out_eval))


def test_vit_dropout_behavior():
    """Dropout: off by default (rate 0 == pre-dropout numerics, no rng
    needed); with rate > 0, train mode is stochastic per rng while eval is
    deterministic and rng-free."""
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    plain = create_model("vit_tiny", depth=2, hidden_dim=32, num_heads=4,
                         mlp_dim=64)
    drop = create_model("vit_tiny", depth=2, hidden_dim=32, num_heads=4,
                        mlp_dim=64, dropout_rate=0.5)
    variables = plain.init(jax.random.PRNGKey(0), x)
    # identical params tree: dropout adds no parameters
    a = plain.apply(variables, x)
    b = drop.apply(variables, x)  # eval mode: dropout inert, no rng needed
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    r1 = drop.apply(variables, x, train=True,
                    rngs={"dropout": jax.random.PRNGKey(1)})
    r2 = drop.apply(variables, x, train=True,
                    rngs={"dropout": jax.random.PRNGKey(2)})
    same = drop.apply(variables, x, train=True,
                      rngs={"dropout": jax.random.PRNGKey(1)})
    assert not np.allclose(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(same))
    assert not np.allclose(np.asarray(r1), np.asarray(a))


def test_lm_dropout_composes_with_remat_and_decode():
    """LM dropout: trains under remat (static train arg through
    jax.checkpoint), and generation (decode) stays deterministic — dropout
    never fires in decode mode."""
    from ddp_practice_tpu.inference import make_cache

    model = create_model(
        "lm_tiny", vocab_size=32, max_len=32, hidden_dim=32, depth=2,
        num_heads=4, mlp_dim=64, dropout_rate=0.3, remat=True,
    )
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (2, 12)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), tokens)
    g = jax.grad(
        lambda p: jnp.sum(
            model.apply({"params": p}, tokens, train=True,
                        rngs={"dropout": jax.random.PRNGKey(3)}) ** 2
        )
    )(variables["params"])
    assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree.leaves(g))

    full = model.apply(variables, tokens)  # eval: deterministic
    cache = make_cache(model, 2, 12)
    logits, _ = model.apply(
        {"params": variables["params"], "cache": cache},
        tokens[:, :5], decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :5]), rtol=2e-5, atol=2e-5
    )
