"""Input pipeline tests: IDX parsing, deterministic shuffling, sharding.

Asserts the DistributedSampler-equivalence contract (SURVEY §1 L2, §2.5):
disjoint per-process shards covering the dataset, (seed, epoch)-keyed
reshuffle, exact (weighted) padding.
"""

import gzip
import struct

import numpy as np
import pytest

from ddp_practice_tpu.data import DataLoader, ShardSpec, epoch_indices, load_dataset
from ddp_practice_tpu.data.datasets import _read_idx, synthetic_image_classification
from ddp_practice_tpu.data.sharding import pad_to_multiple


def _write_idx(path, arr: np.ndarray):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(np.uint8).tobytes())


@pytest.mark.fast
def test_idx_roundtrip(tmp_path):
    arr = np.arange(2 * 4 * 3, dtype=np.uint8).reshape(2, 4, 3)
    p = str(tmp_path / "x-idx3-ubyte")
    _write_idx(p, arr)
    np.testing.assert_array_equal(_read_idx(p), arr)
    # gz variant
    with open(p, "rb") as f:
        raw = f.read()
    with gzip.open(p + ".gz", "wb") as f:
        f.write(raw)
    np.testing.assert_array_equal(_read_idx(p + ".gz"), arr)


def test_epoch_indices_deterministic_and_reshuffled():
    a = epoch_indices(100, seed=3407, epoch=0)
    b = epoch_indices(100, seed=3407, epoch=0)
    c = epoch_indices(100, seed=3407, epoch=1)
    np.testing.assert_array_equal(a, b)          # same (seed, epoch) -> same order
    assert not np.array_equal(a, c)              # set_epoch reshuffles
    np.testing.assert_array_equal(np.sort(a), np.arange(100))  # permutation


@pytest.mark.fast
def test_shards_disjoint_and_cover():
    """Union of per-process batch slices == the full epoch order."""
    n, gbs, nproc = 64, 16, 4
    ds = synthetic_image_classification(
        n=n, image_shape=(4, 4, 1), num_classes=3, seed=0
    )
    seen = []
    for p in range(nproc):
        loader = DataLoader(
            ds, global_batch_size=gbs,
            shard=ShardSpec(p, nproc), seed=1, shuffle=True,
        )
        for batch in loader:
            # recover indices by matching labels+images is overkill; track count
            assert batch["image"].shape == (gbs // nproc, 4, 4, 1)
            seen.append(batch["weight"])
    total = sum(w.sum() for w in seen)
    assert total == n  # every sample weighted exactly once across processes


def test_padding_weights_exact():
    idx = np.arange(10)
    padded, w = pad_to_multiple(idx, 8)
    assert len(padded) == 16
    assert w.sum() == 10
    np.testing.assert_array_equal(padded[:10], idx)


def test_loader_epoch_reshuffle_changes_batches():
    ds = synthetic_image_classification(
        n=32, image_shape=(4, 4, 1), num_classes=3, seed=0
    )
    loader = DataLoader(ds, global_batch_size=8, seed=5, shuffle=True)
    loader.set_epoch(0)
    first0 = next(iter(loader))["image"]
    loader.set_epoch(1)
    first1 = next(iter(loader))["image"]
    assert not np.array_equal(first0, first1)
    loader.set_epoch(0)
    again = next(iter(loader))["image"]
    np.testing.assert_array_equal(first0, again)


def test_synthetic_splits_share_templates():
    tr = load_dataset("synthetic", "/nonexistent", "train", seed=7)
    te = load_dataset("synthetic", "/nonexistent", "test", seed=7)
    # same class templates: per-class means correlate strongly across splits
    for c in range(3):
        m_tr = tr.images[tr.labels == c].mean(0)
        m_te = te.images[te.labels == c].mean(0)
        corr = np.corrcoef(m_tr.ravel(), m_te.ravel())[0, 1]
        assert corr > 0.9, corr
    # but the samples differ
    assert not np.array_equal(tr.images[:8], te.images[:8])


def test_global_batch_not_divisible_raises():
    with pytest.raises(ValueError):
        ShardSpec(0, 3).local_slice(16)
