"""ImageNet-scale input pipeline: uint8 storage, memmap streaming,
on-device normalization (SURVEY §7 Stage 5, BASELINE config 5).

The reference's pipeline is torchvision-in-RAM (origin_main.py:88-107) and
cannot reach ImageNet; these tests pin the properties the array-record
corpus adds: pixels stay uint8 on disk and over H2D, the corpus is
memory-mapped (never materialized as fp32 in host RAM), generation and
loading are (seed, epoch)-deterministic, and the uint8 path is numerically
identical to the fp32 path because normalization happens on device.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_practice_tpu.data import (
    DataLoader,
    load_array_dataset,
    synthetic_imagenet_corpus,
    write_array_dataset,
)
from ddp_practice_tpu.data.datasets import Dataset


def _tiny_corpus(root, split="train", n=16, shape=(64, 64, 3), classes=10):
    return synthetic_imagenet_corpus(
        root, split, n=n, image_shape=shape, num_classes=classes, seed=7,
        chunk_size=5,  # deliberately not dividing n: exercises the tail
    )


def test_writer_loader_roundtrip(tmp_path):
    root = str(tmp_path / "corpus")
    imgs = np.arange(4 * 8 * 8 * 3, dtype=np.uint8).reshape(4, 8, 8, 3)
    lbls = np.array([0, 1, 2, 1], np.int32)
    write_array_dataset(
        root, "train", [(imgs[:3], lbls[:3]), (imgs[3:], lbls[3:])],
        n=4, image_shape=(8, 8, 3), num_classes=3, name="t",
    )
    ds = load_array_dataset(root, "train")
    assert isinstance(ds.images, np.memmap)  # streamed, not loaded
    assert ds.images.dtype == np.uint8
    assert ds.num_classes == 3
    np.testing.assert_array_equal(np.asarray(ds.images), imgs)
    np.testing.assert_array_equal(ds.labels, lbls)


@pytest.mark.fast
def test_writer_rejects_wrong_count(tmp_path):
    root = str(tmp_path / "corpus")
    imgs = np.zeros((2, 4, 4, 1), np.uint8)
    with pytest.raises(ValueError):
        write_array_dataset(
            root, "train", [(imgs, np.zeros(2, np.int32))],
            n=5, image_shape=(4, 4, 1), num_classes=2,
        )


def test_synthetic_corpus_deterministic_and_cached(tmp_path):
    a = _tiny_corpus(str(tmp_path / "a"))
    b = _tiny_corpus(str(tmp_path / "b"))
    np.testing.assert_array_equal(np.asarray(a.images), np.asarray(b.images))
    np.testing.assert_array_equal(a.labels, b.labels)
    # second call on the same root reads the cached files
    a2 = _tiny_corpus(str(tmp_path / "a"))
    np.testing.assert_array_equal(np.asarray(a.images), np.asarray(a2.images))
    assert isinstance(a.images, np.memmap)
    assert a.images.dtype == np.uint8


def test_loader_uint8_batches_and_epoch_determinism(tmp_path):
    ds = _tiny_corpus(str(tmp_path / "c"))
    loader = DataLoader(ds, global_batch_size=4, seed=3407)

    loader.set_epoch(0)
    e0a = [b["image"].copy() for b in loader]
    assert all(b.dtype == np.uint8 for b in e0a)  # uint8 end to end on host
    loader.set_epoch(0)
    e0b = [b["image"] for b in loader]
    for x, y in zip(e0a, e0b):
        np.testing.assert_array_equal(x, y)
    loader.set_epoch(1)
    e1 = np.concatenate([b["image"] for b in loader])
    assert not np.array_equal(np.concatenate(e0a), e1)  # reshuffled


def test_native_gather_matches_numpy_on_uint8_memmap(tmp_path):
    from ddp_practice_tpu.data import native_loader

    if not native_loader.available():
        pytest.skip("native backend not built")
    ds = _tiny_corpus(str(tmp_path / "d"))
    gather = native_loader.make_gather(ds)
    idx = np.array([3, 0, 15, 7, 3], np.int64)
    imgs_n, lbls_n = gather(idx)
    assert imgs_n.dtype == np.uint8  # dtype pass-through, no fp32 blowup
    np.testing.assert_array_equal(imgs_n, np.asarray(ds.images[idx]))
    np.testing.assert_array_equal(lbls_n, ds.labels[idx])
    with pytest.raises(IndexError):
        gather(np.array([99], np.int64))


def test_uint8_path_matches_fp32_path():
    """On-device u8/255 == host fp32 storage: same step, same numbers."""
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train.state import create_state
    from ddp_practice_tpu.train.steps import make_train_step
    import optax

    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(8, 28, 28, 1)).astype(np.uint8)
    labels = rng.integers(0, 10, size=8).astype(np.int32)
    model = create_model("convnet", num_classes=10)
    tx = optax.sgd(1e-2)
    sample = jnp.zeros((8, 28, 28, 1), jnp.float32)

    def run(images):
        state = create_state(
            model, tx, rng=jax.random.PRNGKey(0), sample_input=sample
        )
        step = make_train_step(model, tx)
        batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}
        state, metrics = step(state, batch)
        return state, metrics

    s_u8, m_u8 = run(u8)
    s_f32, m_f32 = run(u8.astype(np.float32) / 255.0)
    assert float(m_u8["loss"]) == pytest.approx(float(m_f32["loss"]), abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
        s_u8.params, s_f32.params,
    )


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_resnet50_trains_on_imagenet_shaped_corpus(tmp_path):
    """The BASELINE config-5 rung: ResNet-50 takes real ImageNet-shaped
    uint8 batches from a memmapped corpus — no fp32 dataset in RAM."""
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train.state import create_state
    from ddp_practice_tpu.train.steps import make_train_step
    import optax

    ds = synthetic_imagenet_corpus(
        str(tmp_path / "imagenet"), "train", n=8,
        image_shape=(224, 224, 3), num_classes=1000, seed=11,
    )
    assert isinstance(ds.images, np.memmap) and ds.images.dtype == np.uint8
    loader = DataLoader(ds, global_batch_size=2, seed=3407, drop_last=True)
    model = create_model("resnet50", num_classes=1000)
    tx = optax.sgd(1e-2)
    state = create_state(
        model, tx, rng=jax.random.PRNGKey(0),
        sample_input=jnp.zeros((2, 224, 224, 3), jnp.float32),
    )
    step = make_train_step(model, tx)
    batch = next(iter(loader))
    assert batch["image"].dtype == np.uint8
    state, metrics = step(
        state, {"image": jnp.asarray(batch["image"]),
                "label": jnp.asarray(batch["label"])},
    )
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_trainer_end_to_end_on_imagenet_corpus(tmp_path):
    """Trainer smoke over dataset='imagenet' (synthetic fallback): uint8
    memmap corpus through sharded loaders, train + exact eval."""
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.train.loop import Trainer

    cfg = TrainConfig(
        model="resnet18",
        dataset="imagenet",
        data_dir=str(tmp_path),
        synthetic_size=36,  # global batch is 2 x 8 devices = 16 -> 3 steps
        epochs=1,
        batch_size=2,
        max_steps_per_epoch=2,
        log_every_steps=0,
        data_placement="host",  # this test is about memmap STREAMING
    )
    trainer = Trainer(cfg)
    assert isinstance(trainer.train_ds.images, np.memmap)
    summary = trainer.fit()
    assert np.isfinite(summary["accuracy"])
    assert summary["steps"] == 2


def test_dataset_rejects_unknown_dtype():
    with pytest.raises(AssertionError):
        Dataset(
            images=np.zeros((2, 4, 4, 1), np.float64),
            labels=np.zeros(2, np.int32),
            num_classes=2,
        )
