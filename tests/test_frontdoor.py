"""Front door wire surface (serve/frontdoor.py + sse.py + admission.py).

Pinned in three tiers, cheapest first:

- host-pure units: the SSE codec survives arbitrary TCP re-chunking,
  the admission controller's token bucket and concurrency cap replay on
  a FakeClock, and a wire capture bridges into the same stream audit
  (tools/check_stream.py) the in-process benches use.
- `net` + stub router: every refusal path (404/400/401/429/503) and
  /healthz run against a real socket but a router that never has to
  exist — the door turns these away before the engine is touched, so
  the test should not pay for an engine either.
- `net` + `slow` e2e: a real router behind the door. Greedy tokens over
  the wire are bit-identical to `router.stream()` in-process, frame ids
  are contiguous with exactly one terminal, drain finishes in-flight
  streams while refusing new ones, and a deliberately throttled reader
  (tiny buffers at every layer) is SHED with a typed `slow_consumer`
  terminal while its request decodes to completion anyway.
"""

import http.client
import json
import threading
import time

import pytest

from ddp_practice_tpu.serve import (
    AdmissionController,
    EngineConfig,
    FakeClock,
    Frontdoor,
    FrontdoorConfig,
    FrontdoorMetrics,
    Request,
    TenantPolicy,
    make_router,
    sse_request,
)
from ddp_practice_tpu.serve.sse import KINDS, SSEParser, encode_event

VOCAB = 32


# ------------------------------------------------------ host-pure units
@pytest.mark.fast
def test_sse_codec_roundtrip_any_chunking():
    """encode_event -> SSEParser is identity no matter how TCP slices
    the byte stream — including one byte at a time."""
    events = [("tokens", 0, {"start": 0, "tokens": [3, 1]}),
              ("resumed", 1, {"start": 2, "tokens": []}),
              ("end", 2, {"start": 2, "tokens": [], "status": "eos"})]
    assert all(k in KINDS for k, _, _ in events)
    wire = b"".join(encode_event(*ev) for ev in events)

    for step in (1, 3, len(wire)):  # pathological, odd, single segment
        p = SSEParser()
        got = []
        for i in range(0, len(wire), step):
            got.extend(p.feed(wire[i:i + step]))
        assert [(e["event"], e["id"], e["data"]) for e in got] == [
            (k, i, d) for k, i, d in events
        ]


@pytest.mark.fast
def test_sse_parser_crlf_comments_and_malformed_payload():
    p = SSEParser()
    # \r\n framing, keep-alive comment line, unknown field — all per
    # spec; a non-JSON data payload surfaces as the raw string so the
    # audit can distinguish malformed from absent
    raw = (b": keep-alive\r\n\r\n"
           b"id: 0\r\nevent: tokens\r\nretry: 5\r\n"
           b"data: {\"tokens\":[7]}\r\n\r\n"
           b"event: end\ndata: not json\n\n")
    got = p.feed(raw)
    assert [(e["id"], e["event"]) for e in got] == [(0, "tokens"),
                                                   (None, "end")]
    assert got[0]["data"] == {"tokens": [7]}
    assert got[1]["data"] == "not json"


@pytest.mark.fast
def test_admission_token_bucket_replays_on_fake_clock():
    clock = FakeClock()
    adm = AdmissionController(
        {"t": TenantPolicy(rate_rps=2.0, burst=2)}, clock=clock
    )
    got = [adm.try_acquire("t") for _ in range(3)]
    assert [g[0] for g in got] == [True, True, False]
    assert got[2][1] == "rate" and adm.refused["rate"] == 1
    clock.advance(0.5)            # exactly one token refilled at 2 rps
    assert adm.try_acquire("t") == (True, None)
    assert adm.try_acquire("t")[1] == "rate"


@pytest.mark.fast
def test_admission_concurrency_cap_checked_before_rate():
    clock = FakeClock()
    adm = AdmissionController(
        {"t": TenantPolicy(rate_rps=100.0, burst=1, max_concurrent=1)},
        clock=clock,
    )
    assert adm.try_acquire("t") == (True, None)
    # over the cap: refused as "concurrency" and must NOT burn the rate
    # token the request was never going to use
    assert adm.try_acquire("t") == (False, "concurrency")
    adm.release("t")
    clock.advance(1.0)
    assert adm.try_acquire("t") == (True, None)
    # unknown tenants fall under the default policy (admit-everything)
    assert adm.try_acquire("someone-else") == (True, None)
    assert adm.inflight("t") == 1


@pytest.mark.fast
def test_wire_capture_bridges_into_stream_audit():
    """The bench's SSE capture format feeds tools/check_stream.py's
    verdict unchanged — one audit for both sides of the socket."""
    from tools.check_stream import sse_to_chunks, stream_verdict

    def rec(stream, i, kind, data):
        return {"stream": stream, "id": i, "event": kind, "data": data}

    good = [
        rec("rid:1", 0, "tokens", {"start": 0, "tokens": [5, 2]}),
        rec("rid:1", 1, "end",
            {"start": 2, "tokens": [9], "status": "length"}),
    ]
    ok, audit = stream_verdict(sse_to_chunks(good))
    assert ok, audit

    gap = [good[0], rec("rid:1", 2, "end",
                        {"start": 2, "tokens": [], "status": "eos"})]
    ok, audit = stream_verdict(sse_to_chunks(gap))
    assert not ok


# ------------------------------------------- refusal paths, stub router
class _StubRouter:
    """The slice of Router the door touches before submit: enough for
    every refusal path and /healthz, with no engine behind it."""

    def __init__(self):
        self.tracked = {}
        self.streams = {}
        self.idle = True
        self._pending = 0
        self.clock = FakeClock()

    def step(self):
        pass

    def states(self):
        return [{"replica": 0, "state": "up"}]


@pytest.fixture
def stub_door():
    adm = AdmissionController(
        {"capped": TenantPolicy(max_concurrent=1)}
    )
    fd = Frontdoor(
        _StubRouter(),
        config=FrontdoorConfig(auth_token="sekrit", max_prompt_len=64),
        admission=adm,
        metrics=FrontdoorMetrics(),
    )
    fd.start()
    yield fd, adm
    fd.close()


@pytest.mark.net
def test_door_refusals_are_typed_json(stub_door):
    fd, adm = stub_door
    auth = {"Authorization": "Bearer sekrit"}

    status, ev = sse_request("127.0.0.1", fd.port, {"prompt": [1, 2]})
    assert status == 401

    # correct token, bad bodies: the 400s prove auth ran first and the
    # validator names the offending field
    for body, needle in (
        ({"prompt": []}, "prompt"),
        ({"prompt": [1, -2]}, "prompt"),
        ({"prompt": [1] * 65}, "too long"),
        ({"prompt": [1, 2], "max_new_tokens": 0}, "max_new_tokens"),
    ):
        status, ev = sse_request("127.0.0.1", fd.port, body, headers=auth)
        assert status == 400, (body, status, ev)
        assert needle in ev[0]["data"]["error"], (body, ev)

    # per-tenant concurrency: hold the only slot, watch the 429
    ok, _ = adm.try_acquire("capped")
    assert ok
    status, ev = sse_request(
        "127.0.0.1", fd.port, {"prompt": [1], "tenant": "capped"},
        headers=auth)
    assert status == 429 and ev[0]["data"]["reason"] == "concurrency"
    adm.release("capped")


@pytest.mark.net
def test_door_fairness_refusal_is_typed_429():
    """The weighted-fair gate at the door (serve/fairshare.py VTC +
    fair_max_inflight): under pressure the MOST-over-served tenant's
    request bounces as a typed 429 "fairness" before it costs a queue
    slot; the starved tenant's identical request still 503s PAST
    admission (no replica) — the refusal is tenant-shaped, not load-
    shaped."""
    from ddp_practice_tpu.serve.fairshare import VirtualTokenCounter

    vtc = VirtualTokenCounter()
    vtc.charge("bulk", decode=100)
    vtc.touch("acme")
    adm = AdmissionController(vtc=vtc, fair_max_inflight=2)
    fd = Frontdoor(_StubRouter(), config=FrontdoorConfig(),
                   admission=adm, metrics=FrontdoorMetrics())
    fd.start()
    try:
        for t in ("bulk", "acme"):   # reach the pressure threshold
            assert adm.try_acquire(t) == (True, None)
        status, ev = sse_request(
            "127.0.0.1", fd.port, {"prompt": [1], "tenant": "bulk"})
        assert status == 429 and ev[0]["data"]["reason"] == "fairness"
        status, ev = sse_request(
            "127.0.0.1", fd.port, {"prompt": [1], "tenant": "acme"})
        assert status != 429    # admitted; fails later for other reasons
        assert adm.refused["fairness"] == 1
    finally:
        fd.close()


@pytest.mark.net
def test_healthz_and_drain_refusal(stub_door):
    fd, _ = stub_door
    conn = http.client.HTTPConnection("127.0.0.1", fd.port, timeout=10)
    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    hz = json.loads(resp.read())
    assert resp.status == 200 and hz["status"] == "ok"
    assert hz["inflight_streams"] == 0 and hz["replicas"]

    conn = http.client.HTTPConnection("127.0.0.1", fd.port, timeout=10)
    conn.request("GET", "/nope")
    assert conn.getresponse().status == 404

    fd.begin_drain()
    status, ev = sse_request(
        "127.0.0.1", fd.port, {"prompt": [1, 2]},
        headers={"Authorization": "Bearer sekrit"})
    assert status == 503 and ev[0]["data"]["error"] == "draining"
    assert fd.drain(timeout_s=5)   # nothing in flight: immediate


# ----------------------------------------------------- socket e2e, slow
@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.mark.net
@pytest.mark.slow
def test_wire_identity_contiguity_and_drain(lm, devices):
    """One router, both sides: greedy reference tokens via
    `router.stream()` in-process, then the SAME router behind the door
    — the socket consumer must see bit-identical tokens, contiguous
    frame ids, exactly one terminal. Then drain: an in-flight stream
    finishes while a new request bounces with 503."""
    import numpy as np

    model, params = lm
    rng = np.random.default_rng(11)
    router = make_router(
        model, params, 1,
        EngineConfig(max_slots=4, prompt_buckets=(8, 16), max_len=96),
    )
    router.warmup()
    prompts = [rng.integers(1, VOCAB, int(rng.integers(4, 14))).tolist()
               for _ in range(5)]
    for i, p in enumerate(prompts):
        router.submit(Request(rid=i, prompt=p, max_new_tokens=8, seed=0))
    router.run_until_idle()
    ref = {i: router.stream(i).tokens() for i in range(len(prompts))}

    fd = Frontdoor(router, config=FrontdoorConfig(max_buffered_events=64))
    fd.start()
    try:
        for i, p in enumerate(prompts):
            status, events = sse_request(
                "127.0.0.1", fd.port,
                {"prompt": p, "max_new_tokens": 8, "seed": 0})
            assert status == 200, (status, events)
            assert [e["id"] for e in events] == list(range(len(events)))
            kinds = [e["event"] for e in events]
            assert kinds.count("end") == 1 and kinds[-1] == "end"
            assert events[-1]["data"]["status"] in ("eos", "length",
                                                    "stop")
            toks = [t for e in events if e["event"] == "tokens"
                    for t in e["data"]["tokens"]]
            toks += events[-1]["data"]["tokens"]
            assert toks == ref[i], (i, toks, ref[i])

        # ---- drain: started stream completes, new request refused
        results = []

        def consume():
            results.append(sse_request(
                "127.0.0.1", fd.port,
                {"prompt": prompts[0], "max_new_tokens": 24, "seed": 0},
                read_delay_s=0.02))

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.2)
        fd.begin_drain()
        status, _ = sse_request("127.0.0.1", fd.port, {"prompt": [1, 2]})
        assert status == 503
        t.join()
        status, events = results[0]
        assert status == 200 and events[-1]["event"] == "end"
        assert fd.drain(timeout_s=15)
    finally:
        fd.close()


@pytest.mark.net
@pytest.mark.slow
def test_slow_consumer_is_shed_not_obeyed(devices):
    """Tiny buffers at every layer (subscriber ring, transport
    watermark, both socket buffers) + a reader sipping one byte at a
    time: delivery is cut with a single typed `slow_consumer` terminal,
    the shed counter ticks, and the request keeps decoding — the router
    drains to idle with no socket holding a KV slot hostage."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_practice_tpu.models import create_model

    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=512, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    router = make_router(
        model, params, 1,
        EngineConfig(max_slots=2, prompt_buckets=(16,), max_len=400),
    )
    router.warmup()
    fd = Frontdoor(router, config=FrontdoorConfig(
        max_buffered_events=2, write_buffer_bytes=256, sndbuf=1))
    fd.start()
    try:
        prompt = np.random.default_rng(0).integers(
            1, VOCAB, 12).tolist()
        status, events = sse_request(
            "127.0.0.1", fd.port,
            {"prompt": prompt, "max_new_tokens": 380, "seed": 0},
            read_delay_s=0.15, rcvbuf=1)
        assert status == 200
        assert events[-1]["event"] == "end"
        assert events[-1]["data"]["status"] == "slow_consumer"
        assert fd.driver.sheds >= 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not router.idle:
            time.sleep(0.05)
        assert router.idle, "shed request did not decode to completion"
    finally:
        fd.close()
