"""Host-pure fault-tolerance machinery: backoff, breaker, fault plans.

No model, no engine — these pin the deterministic substrate the chaos
tests (test_serve_router.py) build on: the shared backoff helper is a
pure function of (seed, attempt), the circuit breaker trips/probes on
an injected clock, and a FaultPlan round-trips through JSON and fires
its specs at exactly the planned ticks.
"""

import math

import pytest

from ddp_practice_tpu.serve.faults import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
    ReplicaCrashed,
)
from ddp_practice_tpu.serve.health import (
    BreakerConfig,
    CircuitBreaker,
    HealthState,
    ReplicaHealth,
)
from ddp_practice_tpu.serve.scheduler import FakeClock
from ddp_practice_tpu.utils.backoff import backoff_delay
from ddp_practice_tpu.utils.metrics import labelled


# ---------------------------------------------------------------- backoff
@pytest.mark.fast
def test_backoff_deterministic_and_capped():
    a = [backoff_delay(i, base_s=0.1, factor=2.0, max_s=1.0, jitter=0.5,
                       seed=7) for i in range(8)]
    b = [backoff_delay(i, base_s=0.1, factor=2.0, max_s=1.0, jitter=0.5,
                       seed=7) for i in range(8)]
    assert a == b  # same (seed, attempt) -> same delay, always
    # geometric growth below the cap: the un-jittered floor doubles
    for i in range(3):
        assert a[i + 1] > a[i]
    # cap holds (jitter may stretch at most (1 + jitter) * max_s)
    assert all(d <= 1.0 * 1.5 for d in a)
    # different seeds de-synchronize (the thundering-herd fix)
    c = backoff_delay(3, base_s=0.1, jitter=0.5, seed=8)
    assert c != a[3]


def test_backoff_no_jitter_is_exact():
    assert backoff_delay(0, base_s=0.5, jitter=0.0) == 0.5
    assert backoff_delay(2, base_s=0.5, factor=2.0, jitter=0.0) == 2.0
    assert backoff_delay(10, base_s=0.5, max_s=3.0, jitter=0.0) == 3.0
    with pytest.raises(ValueError):
        backoff_delay(-1, base_s=0.5)


# ---------------------------------------------------------------- breaker
@pytest.mark.fast
def test_breaker_trips_after_consecutive_failures():
    br = CircuitBreaker(BreakerConfig(trip_after=3, probe_base_s=0.1,
                                      probe_jitter=0.0))
    assert not br.record_failure(0.0)
    br.record_success()  # reset: failures must be CONSECUTIVE
    assert not br.record_failure(1.0)
    assert not br.record_failure(2.0)
    assert br.record_failure(3.0)  # third consecutive -> trip
    assert br.open and br.trips == 1
    # probe schedule: not before base backoff, due after
    assert not br.probe_due(3.05)
    assert br.probe_due(3.1)


def test_breaker_probe_backoff_doubles_then_closes():
    br = CircuitBreaker(BreakerConfig(trip_after=1, probe_base_s=0.1,
                                      probe_factor=2.0, probe_jitter=0.0))
    br.record_failure(0.0)
    assert br.probe_due(0.1)
    br.on_probe(False, 0.1)        # failed probe: wait doubles
    assert not br.probe_due(0.25)  # next probe at 0.1 + 0.2
    assert br.probe_due(0.31)
    br.on_probe(True, 0.31)        # half-open success closes
    assert not br.open and br.consecutive_failures == 0


@pytest.mark.fast
def test_health_state_transitions():
    h = ReplicaHealth(BreakerConfig(trip_after=2, probe_base_s=0.1,
                                    probe_jitter=0.0))
    assert h.state is HealthState.HEALTHY and h.alive
    h.mark_failure(0.0)
    assert h.state is HealthState.DEGRADED and h.alive
    h.mark_success()
    assert h.state is HealthState.HEALTHY
    h.mark_dead(1.0)  # crash path: instant DEAD, no failure count needed
    assert h.state is HealthState.DEAD and not h.alive
    h.on_probe(True, 2.0)
    assert h.state is HealthState.HEALTHY


# ------------------------------------------------------------ fault plans
@pytest.mark.fast
def test_fault_plan_json_roundtrip():
    plan = FaultPlan([
        FaultSpec(kind="crash", tick=5, replica=0, down_s=0.5),
        FaultSpec(kind="nan_logits", tick=3, replica=1, slot=2),
        FaultSpec(kind="latency", tick=2, replica=1, delay_s=0.25),
        FaultSpec(kind="admit_fail", tick=4, replica=0),
    ])
    plan2 = FaultPlan.from_json(plan.to_json())
    assert plan2.faults == plan.faults
    # bare-list schema also accepted
    plan3 = FaultPlan.from_json('[{"kind": "crash", "tick": 1}]')
    assert plan3.faults == [FaultSpec(kind="crash", tick=1)]
    with pytest.raises(ValueError):
        FaultSpec(kind="meteor", tick=1)
    with pytest.raises(ValueError):
        FaultSpec(kind="crash", tick=0)  # ticks are 1-based
    # replicas without faults get no injector (zero scheduler overhead)
    assert plan.injector(2) is None
    assert plan.injector(0) is not None


class _StubEngine:
    def __init__(self):
        self.poisoned = []

    def poison_slot(self, slot):
        self.poisoned.append(slot)


class _StubScheduler:
    def __init__(self, clock):
        self.clock = clock
        self.engine = _StubEngine()


def test_injector_fires_specs_at_planned_ticks():
    clock = FakeClock(step_s=0.01)
    sched = _StubScheduler(clock)
    inj = FaultPlan([
        FaultSpec(kind="latency", tick=2, delay_s=1.0),
        FaultSpec(kind="nan_logits", tick=3, slot=1),
        FaultSpec(kind="admit_fail", tick=4),
        FaultSpec(kind="crash", tick=5, down_s=2.0),
    ]).injector(0)
    inj.on_tick(sched)                      # tick 1: nothing
    assert clock.now() == 0.0 and not sched.engine.poisoned
    inj.on_tick(sched)                      # tick 2: virtual stall
    assert clock.now() == 1.0
    inj.on_tick(sched)                      # tick 3: poison slot 1
    assert sched.engine.poisoned == [1]
    assert not inj.take_admit_fault()       # not scheduled yet
    inj.on_tick(sched)                      # tick 4: one admit failure
    assert inj.take_admit_fault()
    assert not inj.take_admit_fault()       # consumed
    with pytest.raises(ReplicaCrashed):
        inj.on_tick(sched)                  # tick 5: crash, down 2s
    assert not inj.alive(clock.now())
    assert inj.alive(clock.now() + 2.0)     # probeable after the window
    inj.revive()
    assert inj.alive(clock.now())


def test_injector_permanent_crash():
    inj = FaultPlan([FaultSpec(kind="crash", tick=1)]).injector(0)
    with pytest.raises(ReplicaCrashed):
        inj.on_tick(_StubScheduler(FakeClock()))
    assert inj.crashed_until == math.inf
    assert not inj.alive(1e12)


# ----------------------------------------------------------- metric names
@pytest.mark.fast
def test_labelled_metric_names():
    assert labelled("x") == "x"
    assert labelled("serve_sheds_total", reason="brownout") == \
        "serve_sheds_total{reason=brownout}"
    # label order is canonical however kwargs are spelled
    assert labelled("m", b=1, a=2) == labelled("m", a=2, b=1) == "m{a=2,b=1}"
