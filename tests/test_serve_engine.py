"""Slot engine mechanism (serve/engine.py + serve/kv_slots.py).

Pinned: slot allocation/reuse semantics, the shared-cursor position
budget (headroom, epoch reset), prompt bucketing, and the model
contract (RoPE required — left-aligned admission shifts absolute
positions, which only relative encodings survive).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, SlotEngine
from ddp_practice_tpu.serve.kv_slots import SlotAllocator

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _engine(lm, **kw):
    model, params = lm
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("prompt_buckets", (8,))
    return SlotEngine(model, params, EngineConfig(**kw))


@pytest.mark.fast
def test_allocator_reuses_freed_slots(devices):
    a = SlotAllocator(2)
    s0, s1 = a.alloc(), a.alloc()
    assert (s0, s1) == (0, 1) and a.alloc() is None
    a.free(s0)
    assert a.num_used == 1 and a.alloc() == 0  # the freed slot comes back
    with pytest.raises(ValueError):
        a.free(7)


@pytest.mark.fast
def test_engine_requires_rope(devices):
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128,  # learned positions
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="rope"):
        SlotEngine(model, params, EngineConfig())


def test_slot_reuse_after_release(devices, lm):
    """A released slot's successor generates correctly — the admission
    overwrite makes the previous occupant's cache invisible."""
    from ddp_practice_tpu.inference import make_generate_fn

    model, params = lm
    eng = _engine(lm)
    s0 = eng.admit([3, 1, 4])
    s1 = eng.admit([2, 7])
    for _ in range(4):
        eng.step()
    eng.release(s0)
    s2 = eng.admit([5, 5, 1, 2])   # must land in the freed slot
    assert s2 == s0
    n = 5
    got = [int(eng.step()[s2]) for _ in range(n)]
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    want = np.asarray(gen(params, jnp.asarray([[5, 5, 1, 2]], jnp.int32)))
    assert got == want[0, 4:].tolist()


def test_admit_when_full_raises(devices, lm):
    eng = _engine(lm)
    eng.admit([1]), eng.admit([2])
    with pytest.raises(RuntimeError, match="free slot"):
        eng.admit([3])


@pytest.mark.fast
def test_bucket_selection_and_overflow(devices, lm):
    eng = _engine(lm, prompt_buckets=(4, 8))
    assert eng.bucket_for(1) == 4
    assert eng.bucket_for(5) == 8
    with pytest.raises(ValueError, match="bucket"):
        eng.bucket_for(9)


def test_headroom_and_epoch_reset(devices, lm):
    eng = _engine(lm, max_len=24, prompt_buckets=(8,))
    assert eng.cursor == 8 and eng.headroom == 16
    s = eng.admit([1, 2, 3])
    eng.step()
    assert eng.headroom == 15
    with pytest.raises(RuntimeError, match="active slots"):
        eng.reset_epoch()
    eng.release(s)
    eng.reset_epoch()
    assert eng.cursor == 8 and eng.headroom == 16
    # the pool is fully usable again after the rewind
    s2 = eng.admit([4, 4])
    tok = eng.step()
    assert 0 <= int(tok[s2]) < VOCAB


def test_decode_burst_matches_single_steps(devices, lm):
    """A K-step burst dispatch emits exactly the K tokens that K
    token-granular steps would — multi-step scheduling changes dispatch
    cost, not tokens."""
    single = _engine(lm)
    s = single.admit([3, 1, 4, 1, 5])
    want = [int(single.step()[s]) for _ in range(8)]

    burst = _engine(lm, decode_burst=4)
    sb = burst.admit([3, 1, 4, 1, 5])
    got = []
    for _ in range(2):
        got.extend(int(row[sb]) for row in burst.step_burst())
    assert got == want
    assert burst.cursor == single.cursor
    with pytest.raises(RuntimeError, match="decode_burst"):
        burst.step()  # token-granular stepping needs decode_burst=1


def test_decode_shapes_stable_across_churn(devices, lm):
    """Admission/release churn leaves exactly one decode program and one
    prefill program per bucket width in the jit caches."""
    eng = _engine(lm, prompt_buckets=(4, 8))
    for i in range(6):
        s = eng.admit([1 + i] * (2 if i % 2 else 6))  # both buckets in play
        eng.step()
        eng.release(s)
    stats = eng.compile_stats()
    assert stats == {"prefill_compiles": 2, "decode_compiles": 1}
