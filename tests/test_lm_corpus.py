"""LM token corpus / loader / Trainer-e2e tests (data/lm_corpus.py).

Pins the LM task's data contract (the analogue of tests/test_data.py for
images): deterministic (seed, epoch) window plans, disjoint per-process
shards, byte corpora from real files, and the end-to-end training
contract on the synthetic Markov corpus — perplexity must fall from
uniform (= vocab) to near the chain's entropy floor.
"""

import jax
import numpy as np
import pytest

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.data.lm_corpus import (
    LMDataLoader,
    TokenCorpus,
    load_text_corpus,
    synthetic_token_corpus,
)
from ddp_practice_tpu.data.sharding import ShardSpec


@pytest.mark.fast
def test_synthetic_corpus_deterministic():
    a = synthetic_token_corpus(4096, seed=7)
    b = synthetic_token_corpus(4096, seed=7)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    assert a.vocab_size == 64
    c = synthetic_token_corpus(4096, seed=8)
    assert not np.array_equal(a.tokens, c.tokens)


def test_text_corpus_bytes_roundtrip(tmp_path):
    data = b"hello tpu world\x00\xff" * 10
    (tmp_path / "a.txt").write_bytes(data)
    corpus = load_text_corpus(str(tmp_path / "a.txt"))
    np.testing.assert_array_equal(
        corpus.tokens, np.frombuffer(data, dtype=np.uint8)
    )
    assert corpus.vocab_size == 256
    # directory mode concatenates files sorted
    (tmp_path / "b.txt").write_bytes(b"second")
    both = load_text_corpus(str(tmp_path))
    assert len(both) == len(data) + 6


def test_text_corpus_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_text_corpus(str(tmp_path / "nope"))


@pytest.mark.fast
def test_loader_windows_disjoint_and_deterministic():
    corpus = synthetic_token_corpus(4096, seed=0)
    loader = LMDataLoader(
        corpus, seq_len=15, global_batch_size=8, seed=11, shuffle=True
    )
    loader.set_epoch(3)
    b1 = [b["tokens"].copy() for b in loader]
    b2 = [b["tokens"].copy() for b in loader]
    assert len(b1) == loader.steps_per_epoch > 0
    for x, y in zip(b1, b2):
        np.testing.assert_array_equal(x, y)  # same epoch -> same batches
    loader.set_epoch(4)
    b3 = [b["tokens"].copy() for b in loader]
    assert not all(np.array_equal(x, y) for x, y in zip(b1, b3))
    # every batch row is a contiguous window at a window-aligned offset
    flat = corpus.tokens
    w = 16
    for batch in b1:
        for row in batch["tokens"] if isinstance(batch, dict) else batch:
            starts = np.flatnonzero(
                np.all(
                    np.lib.stride_tricks.sliding_window_view(flat, w) == row,
                    axis=1,
                )
            )
            assert any(s % w == 0 for s in starts)


def test_loader_shards_partition_the_global_batch():
    corpus = synthetic_token_corpus(8192, seed=0)

    def batches(spec):
        loader = LMDataLoader(
            corpus, seq_len=15, global_batch_size=8, shard=spec, seed=5
        )
        return list(loader)

    full = batches(ShardSpec())
    p0 = batches(ShardSpec(0, 2))
    p1 = batches(ShardSpec(1, 2))
    for f, a, b in zip(full, p0, p1):
        np.testing.assert_array_equal(
            f["tokens"], np.concatenate([a["tokens"], b["tokens"]])
        )


def test_loader_too_small_corpus_raises():
    corpus = synthetic_token_corpus(256, seed=0)
    with pytest.raises(ValueError, match="fewer than one global batch"):
        LMDataLoader(corpus, seq_len=63, global_batch_size=32)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_lm_fit_end_to_end_reaches_entropy_floor(devices):
    """One epoch of lm_tiny on the Markov corpus: held-out perplexity must
    land far below uniform (vocab 64) — the chain's conditional entropy is
    ~1 bit, so anything under 4 means the model learned the structure."""
    from ddp_practice_tpu.train.loop import Trainer

    cfg = TrainConfig(
        model="lm_tiny", dataset="synthetic_text", epochs=1, batch_size=4,
        seq_len=64, synthetic_size=65536, optimizer="adamw",
        learning_rate=3e-3, log_every_steps=0, mesh=MeshConfig(data=-1),
    )
    tr = Trainer(cfg)
    assert tr.task == "lm"
    summary = tr.fit()
    assert summary["perplexity"] < 4.0, summary
    assert summary["accuracy"] > 0.4, summary
    assert summary["steps"] == tr.train_loader.steps_per_epoch


def test_lm_default_corpus_scales_with_mesh(devices):
    """The reference-default CLI config (batch 32/replica) on a full
    8-device mesh: the synthetic corpus must scale so BOTH splits hold at
    least one global batch of windows (global batch 256 here)."""
    from ddp_practice_tpu.train.loop import Trainer

    cfg = TrainConfig(
        model="lm_tiny", dataset="synthetic_text", batch_size=32,
        seq_len=256, mesh=MeshConfig(data=-1),
    )
    tr = Trainer(cfg)
    assert tr.train_loader.steps_per_epoch >= 1
    assert tr.eval_loader.steps_per_epoch >= 1


def test_lm_label_smoothing_threads_through(devices):
    """--label_smoothing must reach the LM objective (it was once silently
    dropped): smoothed loss differs from unsmoothed on the same batch."""
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import make_lm_train_step

    model = create_model("lm_tiny", vocab_size=32, max_len=32,
                         hidden_dim=32, depth=1, num_heads=2, mlp_dim=64)
    tx = make_optimizer(TrainConfig(optimizer="sgd", learning_rate=1e-2))
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (4, 17)), np.int32
    )}

    def loss_with(ls):
        state = create_state(
            model, tx, rng=jax.random.PRNGKey(0),
            sample_input=jnp.zeros((1, 16), jnp.int32),
        )
        _, m = make_lm_train_step(model, tx, label_smoothing=ls)(state, batch)
        return float(m["loss"])

    assert loss_with(0.0) != loss_with(0.5)


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_lm_resident_matches_host_path(devices):
    """The HBM-resident LM driver (token stream + on-device window gather,
    LMDataLoader.epoch_plan) is an optimization, not a math change: same
    (seed, epoch) windows, same final params (to float noise) and the same
    eval numbers as the host-streamed path."""
    from ddp_practice_tpu.train.loop import Trainer

    base = TrainConfig(
        model="lm_tiny", dataset="synthetic_text", batch_size=4, seq_len=32,
        epochs=1, max_steps_per_epoch=6, optimizer="adamw",
        learning_rate=1e-3, log_every_steps=0, mesh=MeshConfig(data=-1),
    )
    host = Trainer(base.replace(data_placement="host"))
    s_host = host.fit()
    dev = Trainer(base.replace(data_placement="device"))
    assert dev.resident_train_step is not None  # really the resident driver
    s_dev = dev.fit()
    for a, b in zip(
        jax.tree.leaves(jax.device_get(host.state.params)),
        jax.tree.leaves(jax.device_get(dev.state.params)),
    ):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(
        s_dev["accuracy"], s_host["accuracy"], atol=1e-6
    )
    np.testing.assert_allclose(
        s_dev["perplexity"], s_host["perplexity"], rtol=1e-4
    )


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_lm_trainer_text_dataset(devices, tmp_path):
    """dataset='text': the Trainer trains a byte-level LM on real files."""
    from ddp_practice_tpu.train.loop import Trainer

    # a structured byte stream (repeating motif) so one epoch learns
    motif = bytes(range(65, 91)) * 40
    (tmp_path / "corpus.txt").write_bytes(motif * 32)
    cfg = TrainConfig(
        model="lm_tiny", dataset="text", data_dir=str(tmp_path), epochs=1,
        batch_size=4, seq_len=32, optimizer="adamw", learning_rate=3e-3,
        log_every_steps=0, max_steps_per_epoch=20, mesh=MeshConfig(data=-1),
    )
    tr = Trainer(cfg)
    assert tr.train_loader.corpus.vocab_size == 256
    summary = tr.fit()
    assert np.isfinite(summary["perplexity"])
    assert summary["steps"] == 20
