"""SLO burn-rate watchdog (serve/slo.py): trip/resolve semantics under
an injected clock, config round-trips, and THE chaos acceptance pin —
a 2-replica fault-plan run whose SLO alert/resolve instants land in a
validator-clean trace and whose streamed telemetry JSONL renders the
violation through tools/check_slo.py.

The window tests are pure host math on synthetic events (no engines):
fast-window trip, slow-window resolve, and no flapping when burn hovers
in the hysteresis band between `resolve_burn` and `trip_burn`.
"""

import json

import pytest

from ddp_practice_tpu.serve.scheduler import Completion
from ddp_practice_tpu.serve.slo import SLOConfig, SLOWatchdog, classify
from ddp_practice_tpu.utils.metrics import MetricsRegistry
from ddp_practice_tpu.utils.trace import TraceRecorder

CFG = SLOConfig(
    error_rate=0.1, fast_window_s=1.0, slow_window_s=5.0,
    trip_burn=2.0, resolve_burn=1.0, min_events=3,
)


def _feed(wd, t0, n, status, spacing=0.01):
    for i in range(n):
        wd.observe_event(t=t0 + i * spacing, status=status)


# --------------------------------------------------------------- config
def test_config_from_json_string_path_and_dict(tmp_path):
    want = SLOConfig(ttft_p99_s=0.5, error_rate=0.01)
    assert SLOConfig.from_json('{"ttft_p99_s": 0.5, "error_rate": 0.01}') \
        == want
    p = tmp_path / "slo.json"
    p.write_text(want.to_json())
    assert SLOConfig.from_json(str(p)) == want
    assert SLOConfig.from_json(json.loads(want.to_json())) == want
    with pytest.raises(ValueError):
        SLOConfig.from_json('{"nonsense_key": 1}')
    with pytest.raises(ValueError):
        SLOConfig.from_json("no-such-file.json")
    with pytest.raises(ValueError):  # hysteresis band must be a band
        SLOConfig(error_rate=0.1, trip_burn=1.0, resolve_burn=2.0)
    with pytest.raises(ValueError):  # zero budget = infinite burn
        SLOConfig(availability=1.0).objectives()
    with pytest.raises(ValueError):
        SLOConfig().objectives()  # nothing enabled


def test_classify_judges_only_measured_latencies():
    cfg = SLOConfig(ttft_p99_s=0.5, availability=0.9)
    assert classify(cfg, status="length", ttft=0.6) == {
        "ttft_p99": True, "availability": False,
    }
    # no TTFT measured (never produced a token): the latency objective
    # abstains; the failure is availability's business alone
    assert classify(cfg, status="shed", ttft=None) == {
        "availability": True,
    }


# ----------------------------------------------------------- windowing
def test_fast_window_trip():
    reg = MetricsRegistry()
    wd = SLOWatchdog(CFG, registry=reg)
    _feed(wd, 0.0, 5, "error")  # 100% bad vs 10% budget: burn 10
    assert not wd.active
    wd.evaluate(0.1)
    assert wd.active
    assert [e for _, e, _ in wd.alert_log] == ["trip"]
    assert reg.snapshot()["slo_alerts_total"] == 1
    assert reg.snapshot()[
        'slo_alert_active{objective=error_rate}'] == 1.0
    # burn gauges track both windows
    assert reg.snapshot()[
        'slo_burn_rate{objective=error_rate,window=fast}'] == 10.0


def test_min_events_gate_blocks_noise_trips():
    wd = SLOWatchdog(CFG)
    _feed(wd, 0.0, 2, "error")  # only 2 events < min_events=3
    wd.evaluate(0.1)
    assert not wd.active


def test_slow_window_resolve():
    wd = SLOWatchdog(CFG)
    _feed(wd, 0.0, 5, "error")
    wd.evaluate(0.1)
    assert wd.active
    # the burst leaves the fast window almost immediately, but the
    # alert HOLDS until the slow window clears — resolve is slow by
    # design (fast resolve + fast trip = flapping)
    wd.evaluate(2.0)
    assert wd.active
    # dilute the slow window with good traffic: 5 bad / 50 total = 10%
    # bad = budget exactly -> burn 1.0 <= resolve_burn -> resolve
    _feed(wd, 2.0, 45, "eos")
    wd.evaluate(2.6)
    assert not wd.active
    assert [e for _, e, _ in wd.alert_log] == ["trip", "resolve"]


def test_no_flapping_in_the_hysteresis_band():
    """Burn held between resolve_burn (1.0) and trip_burn (2.0) must
    move NEITHER edge: an active alert stays active, a resolved one
    stays resolved."""
    wd = SLOWatchdog(CFG)
    _feed(wd, 0.0, 10, "error")
    wd.evaluate(0.2)
    assert wd.active and len(wd.alert_log) == 1
    # steady state at burn 1.5 (15% bad vs 10% budget), rebuilt inside
    # every window: the alert must hold, not flap
    t = 0.3
    for _ in range(8):
        _feed(wd, t, 3, "error", spacing=0.001)
        _feed(wd, t + 0.01, 17, "eos", spacing=0.001)
        t += 0.5
        wd.evaluate(t)
    assert wd.active
    assert len(wd.alert_log) == 1  # no resolve, no re-trip
    # now genuinely clear, resolve once, and band-burn again: the
    # resolved state must also hold through the band
    wd.evaluate(t + 6.0)  # every event aged out of the slow window
    assert not wd.active and len(wd.alert_log) == 2
    t += 6.0
    for _ in range(4):
        _feed(wd, t, 3, "error", spacing=0.001)
        _feed(wd, t + 0.01, 17, "eos", spacing=0.001)
        t += 0.5
        wd.evaluate(t)
    assert not wd.active  # burn 1.5 < trip_burn: no re-trip
    assert len(wd.alert_log) == 2


def test_latency_objective_burns_on_p99_violations():
    cfg = SLOConfig(ttft_p99_s=0.5, fast_window_s=1.0, slow_window_s=5.0,
                    trip_burn=2.0, resolve_burn=1.0, min_events=3)
    wd = SLOWatchdog(cfg)
    for i in range(10):  # every TTFT over target: burn 1/0.01 = 100
        wd.observe_event(t=0.01 * i, status="length", ttft=0.8)
    wd.evaluate(0.2)
    assert wd.active
    # exactly-at-budget traffic (1% over target) resolves once the
    # storm ages out of the slow window
    wd.evaluate(6.0)
    assert not wd.active


# ------------------------------------------------- chaos acceptance pin
@pytest.mark.chaos
def test_chaos_slo_telemetry_e2e(tmp_path):
    """THE acceptance pin (ISSUE 5): a 2-replica fault-plan run with an
    SLO config trips a burn-rate alert whose alert/resolve instants
    appear in a validator-clean trace, and tools/check_slo.py renders
    the violation from the streamed JSONL — the whole plane, live, on
    FakeClock replicas."""
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.serve import (
        EngineConfig,
        FakeClock,
        FaultPlan,
        FaultSpec,
        Request,
        RouterConfig,
        make_router,
    )
    from ddp_practice_tpu.utils.telemetry import TelemetryExporter
    from tools.check_slo import load_events, slo_report
    from tools.check_traces import parse_stream_text, validate

    vocab = 32
    model = create_model(
        "lm_tiny", vocab_size=vocab, max_len=96, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    cfg = EngineConfig(max_slots=2, max_len=96, prompt_buckets=(8,),
                       temperature=0.0)
    clock = FakeClock(step_s=0.01)
    path = str(tmp_path / "chaos.jsonl")
    reg = MetricsRegistry()
    exporter = TelemetryExporter(path, registry=reg, clock=clock,
                                 start=False)
    tracer = TraceRecorder(clock=clock)
    exporter.attach(tracer)
    slo_cfg = SLOConfig(
        error_rate=0.05, fast_window_s=0.3, slow_window_s=1.0,
        trip_burn=2.0, resolve_burn=1.0, min_events=3,
    )
    watchdog = SLOWatchdog(slo_cfg, clock=clock, registry=reg,
                           tracer=tracer, telemetry=exporter)
    # replica 0 spews NaN logits across several ticks; with a zero
    # retry budget each poisoned request terminates "error" — the SLO's
    # bad events. trip_after is out of reach so the breaker never hides
    # the errors by killing the replica.
    plan = FaultPlan([
        FaultSpec(kind="nan_logits", tick=t, replica=0, slot=t % 2)
        for t in (2, 3, 4, 5)
    ])
    router = make_router(
        model, params, 2, cfg, clock=clock, max_queue=64,
        config=RouterConfig(max_retries=0, retry_jitter=0.0,
                            trip_after=100),
        fault_plan=plan, registry=reg, tracer=tracer,
        slo=watchdog, telemetry=exporter,
    )
    router.warmup()
    tracer.clear()
    for rid in range(10):
        router.submit(Request(rid=rid, prompt=[1 + rid % 7, 2],
                              max_new_tokens=6))
    router.run_until_idle()
    statuses = {c.rid: c.status for c in router.completions}
    assert sum(s == "error" for s in statuses.values()) >= 1
    assert watchdog.active, "burn-rate alert must have tripped"
    # drain the fleet past the slow window: the alert resolves
    for _ in range(300):
        router.step()
        if not watchdog.active:
            break
    assert not watchdog.active
    edges = [e for _, e, _ in watchdog.alert_log]
    assert edges == ["trip", "resolve"]
    exporter.close()

    # the exit-time Chrome dump AND the streamed JSONL both validate,
    # both carrying the alert edges
    dump = tracer.to_chrome_trace()
    assert validate(dump) == []
    names = {ev["name"] for ev in dump["traceEvents"]}
    assert {"slo_alert", "slo_resolve"} <= names
    streamed, truncated, errors = parse_stream_text(open(path).read())
    assert errors == [] and not truncated
    assert validate(streamed) == []
    snames = {ev["name"] for ev in streamed["traceEvents"]}
    assert {"slo_alert", "slo_resolve"} <= snames

    # and the offline tool renders the violation from the same stream
    records, truncated = load_events(path)
    assert not truncated
    report = slo_report(records, slo_cfg)
    assert not report["ok"]
    assert not report["objectives"]["error_rate"]["met"]
    assert report["trips"] == 1
    # metrics snapshots streamed too (close() wrote at least one), and
    # nothing was dropped on the way
    kinds = {r["kind"] for r in records}
    assert "metrics" in kinds and "flight" in kinds
    assert exporter.dropped == 0


def test_offline_verdict_skips_slo_exempt_flights():
    """Online/offline agreement: the router's own brown-out sheds are
    slo_exempt (anti-windup — the live watchdog never judges them), so
    the offline verdict must skip them too; a GENUINE shed still
    counts."""
    from tools.check_slo import slo_report

    cfg = SLOConfig(availability=0.95)
    records = [
        *[{"kind": "flight", "t": 0.01 * i, "status": "length"}
          for i in range(9)],
        {"kind": "flight", "t": 0.2, "status": "shed",
         "slo_exempt": True},
    ]
    rep = slo_report(records, cfg)
    assert rep["ok"] and rep["slo_exempt"] == 1 and rep["flights"] == 9
    records.append({"kind": "flight", "t": 0.3, "status": "shed"})
    rep = slo_report(records, cfg)
    assert not rep["ok"]  # 9/10 judged = 0.9 < 0.95


def test_alert_edges_reach_tracer_and_completions_feed():
    clock = {"t": 0.0}
    tracer = TraceRecorder(clock=lambda: clock["t"])
    tracer.set_process_name(-1, "router")
    wd = SLOWatchdog(CFG, tracer=tracer)
    for i in range(5):
        wd.observe(Completion(
            rid=i, tokens=[], status="error", arrival=0.0,
            finish=0.01 * i,
        ))
    wd.evaluate(0.1)
    clock["t"] = 6.0
    wd.evaluate(6.0)
    names = [ev["name"] for ev in tracer.to_chrome_trace()["traceEvents"]]
    assert "slo_alert" in names and "slo_resolve" in names
