"""Packed decode-attention kernel (ops/decode_attention.py): numerics
pinned to the masked XLA reference on the CPU backend (interpret mode),
covering the single-block fast path, the multi-block online-softmax
path, prefix masking, and left-padded (attn_start) prompts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.ops.attention import attention_with_mask
from ddp_practice_tpu.ops.decode_attention import decode_attention_packed

B, H, HD = 3, 4, 64


def _setup(L, cur, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, 1, H * HD)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(B, L, H * HD)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(B, L, H * HD)), jnp.float32)
    return q, kc, vc, jnp.int32(cur)


def _reference(q, kc, vc, cur, attn_start=None):
    L = kc.shape[1]
    mask = jnp.arange(L)[None, :] <= cur[..., None]
    if attn_start is not None:
        mask = mask[None] & (
            jnp.arange(L)[None, None, :] >= attn_start[:, None, None]
        )
        mask = mask[:, None]
    q4 = q.reshape(B, 1, H, HD)
    k4 = kc.reshape(B, -1, H, HD)
    v4 = vc.reshape(B, -1, H, HD)
    return attention_with_mask(q4, k4, v4, mask).reshape(B, 1, H * HD)


@pytest.mark.parametrize("L,cur", [(256, 0), (256, 100), (256, 255)])
@pytest.mark.fast
def test_single_block_matches_reference(L, cur):
    q, kc, vc, c = _setup(L, cur)
    got = decode_attention_packed(q, kc, vc, c, n_heads=H)
    want = _reference(q, kc, vc, jnp.asarray(cur))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("cur", [3, 700, 1500])
def test_multi_block_matches_reference(cur):
    """L > single_block_max exercises the online-softmax sweep with
    blocks past `cur` skipped (their DMA pinned to block 0)."""
    L = 2048
    q, kc, vc, c = _setup(L, cur, seed=1)
    got = decode_attention_packed(q, kc, vc, c, n_heads=H,
                                  block_l=512, single_block_max=1024)
    want = _reference(q, kc, vc, jnp.asarray(cur))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("L", [256, 2048])
def test_attn_start_left_padding(L):
    """Per-sequence first-valid-key masking (left-padded prompts)."""
    cur = min(L - 1, 900)
    q, kc, vc, c = _setup(L, cur, seed=2)
    start = jnp.asarray([0, 5, min(cur, 60)], jnp.int32)
    got = decode_attention_packed(q, kc, vc, c, start, n_heads=H,
                                  single_block_max=1024)
    want = _reference(q, kc, vc, jnp.asarray(cur), attn_start=start)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_rejects_multi_row_queries():
    q, kc, vc, c = _setup(128, 4)
    q2 = jnp.concatenate([q, q], axis=1)
    with pytest.raises(ValueError, match="single-token"):
        decode_attention_packed(q2, kc, vc, c, n_heads=H)


def test_rejects_unpackable_heads():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 1, 3 * 64)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(1, 64, 3 * 64)), jnp.float32)
    with pytest.raises(ValueError, match="pack"):
        decode_attention_packed(q, kc, kc, jnp.int32(0), n_heads=3)


def test_q8_broadcast_matches_plain():
    """The q8 MXU-broadcast branch of attention_with_mask (live on TPU
    for unpackable head shapes) must equal the plain 1-row path — pinned
    here directly since the backend gate keeps it off the CPU suite."""
    from ddp_practice_tpu.ops.attention import _attention, _q8_attention

    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.normal(size=(2, 1, 3, 32)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 40, 3, 32)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 40, 3, 32)), jnp.float32)
    mask = (jnp.arange(40)[None, :] <= 17)[None, None]
    want = _attention(q, k, v, causal=False, mask=mask)
    got = _q8_attention(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_int8_kv_cache_decode_close_to_full_forward(devices):
    """kv_cache_dtype='int8': cached decode through the quantized packed
    kernel tracks the full forward within quantization tolerance (~1%
    relative — per-(batch, head, position) symmetric scales), and the
    cache actually stores int8."""
    import numpy as np

    from ddp_practice_tpu.inference import make_cache
    from ddp_practice_tpu.models import create_model

    VOCAB, TOTAL = 32, 16
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    kw = dict(vocab_size=VOCAB, max_len=TOTAL, hidden_dim=64, depth=2,
              num_heads=1, mlp_dim=128)
    m_q = create_model("lm_tiny", kv_cache_dtype="int8", **kw)
    m_ref = create_model("lm_tiny", **kw)
    params = m_ref.init(jax.random.PRNGKey(0), tokens)["params"]
    full = m_ref.apply({"params": params}, tokens)

    cache = make_cache(m_q, 2, TOTAL)
    kc = cache["block0"]["attn"]["cached_key"]
    assert kc.dtype == jnp.int8
    assert cache["block0"]["attn"]["cached_key_scale"].shape == (2, 1, TOTAL)
    logits, st = m_q.apply({"params": params, "cache": cache},
                           tokens[:, :8], decode=True, mutable=["cache"])
    outs = [logits]
    for i in range(8, tokens.shape[1]):
        lg, st = m_q.apply({"params": params, **st},
                           tokens[:, i:i + 1], decode=True,
                           mutable=["cache"])
        outs.append(lg)
    got = jnp.concatenate(outs, axis=1)
    rel = float(jnp.max(jnp.abs(got - full))
                / (jnp.max(jnp.abs(full)) + 1e-9))
    assert rel < 0.05, rel


# ----------------------------------------------------------------- paged
# PagedAttention-style path (serve/kv_pages.py layout): the kernel walks
# per-slot page tables instead of a contiguous cache; pinned against the
# gather reference, which is itself pinned against attention_with_mask
# by construction (it calls it).


def _paged_setup(nb, bs, mb, seed=0):
    from ddp_practice_tpu.ops.decode_attention import (
        paged_attention_reference,
        paged_decode_attention,
    )

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, 1, H * HD)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(nb, bs, H * HD)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(nb, bs, H * HD)), jnp.float32)
    pt = jnp.asarray(rng.integers(1, nb, size=(B, mb)), jnp.int32)
    return q, kp, vp, pt, paged_attention_reference, paged_decode_attention


@pytest.mark.fast
def test_paged_kernel_matches_reference():
    """Interpret-mode paged kernel == gather reference across slots at
    different lengths (block-skip masking, per-slot cursors)."""
    q, kp, vp, pt, ref_fn, kern_fn = _paged_setup(nb=12, bs=16, mb=4)
    lengths = jnp.asarray([0, 37, 63], jnp.int32)
    ref = ref_fn(q, kp, vp, pt, lengths, None, n_heads=H)
    got = kern_fn(q, kp, vp, pt, lengths, None, n_heads=H, impl="kernel")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_paged_kernel_respects_attn_start():
    """Left-padded prompts in slot-local coordinates: positions before
    attn_start[b] never contribute."""
    q, kp, vp, pt, ref_fn, kern_fn = _paged_setup(nb=9, bs=16, mb=3, seed=3)
    lengths = jnp.asarray([5, 20, 47], jnp.int32)
    start = jnp.asarray([2, 0, 17], jnp.int32)
    ref = ref_fn(q, kp, vp, pt, lengths, start, n_heads=H)
    got = kern_fn(q, kp, vp, pt, lengths, start, n_heads=H, impl="kernel")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the masked positions actually matter: corrupting a pre-start row
    # changes nothing, corrupting an in-window row changes the output
    b0_block = int(pt[0, 0])
    kp_bad = kp.at[b0_block, 0].add(100.0)   # position 0 < start[0]=2
    same = kern_fn(q, kp_bad, vp, pt, lengths, start, n_heads=H,
                   impl="kernel")
    np.testing.assert_allclose(np.asarray(same)[0], np.asarray(got)[0],
                               atol=2e-5, rtol=2e-5)
    kp_bad2 = kp.at[b0_block, 3].add(100.0)  # position 3 in [2, 5]
    diff = kern_fn(q, kp_bad2, vp, pt, lengths, start, n_heads=H,
                   impl="kernel")
    assert float(jnp.abs(diff[0] - got[0]).max()) > 1e-3


def test_paged_int8_kernel_matches_dequantized_reference():
    """INT8 block pool with per-block (num_blocks, h, block_size) scale
    pages: the quantized page-walking kernel (interpret mode) tracks
    the dequantizing gather reference — the numerics pin behind the
    kv_cache_dtype='int8' paged serving path (halved KV bytes/token)."""
    from ddp_practice_tpu.ops.decode_attention import (
        paged_attention_reference,
        paged_decode_attention,
    )

    rng = np.random.default_rng(7)
    nb, bs, mb = 10, 16, 4
    q = jnp.asarray(rng.normal(size=(B, 1, H * HD)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, size=(nb, bs, H * HD)),
                     jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, size=(nb, bs, H * HD)),
                     jnp.int8)
    ks = jnp.asarray(np.abs(rng.normal(size=(nb, H, bs))) * 0.01 + 1e-3,
                     jnp.float32)
    vs = jnp.asarray(np.abs(rng.normal(size=(nb, H, bs))) * 0.01 + 1e-3,
                     jnp.float32)
    pt = jnp.asarray(rng.integers(1, nb, size=(B, mb)), jnp.int32)
    lengths = jnp.asarray([0, 37, 63], jnp.int32)
    start = jnp.asarray([0, 5, 17], jnp.int32)
    ref = paged_attention_reference(q, kq, vq, pt, lengths, start,
                                    n_heads=H, k_scale=ks, v_scale=vs)
    got = paged_decode_attention(q, kq, vq, pt, lengths, start, n_heads=H,
                                 k_scale=ks, v_scale=vs, impl="kernel")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # missing v_scale refuses loudly rather than serving garbage
    with pytest.raises(ValueError, match="BOTH"):
        paged_decode_attention(q, kq, vq, pt, lengths, None, n_heads=H,
                               k_scale=ks)


def test_paged_single_token_contract():
    """Multi-token queries refuse loudly (prefill is the scratch-cache
    path), and unpackable heads refuse the kernel but serve the
    reference through the auto dispatch."""
    from ddp_practice_tpu.ops.decode_attention import paged_decode_attention

    rng = np.random.default_rng(0)
    kp = jnp.asarray(rng.normal(size=(4, 16, H * HD)), jnp.float32)
    pt = jnp.zeros((B, 2), jnp.int32)
    lengths = jnp.zeros((B,), jnp.int32)
    q2 = jnp.asarray(rng.normal(size=(B, 2, H * HD)), jnp.float32)
    with pytest.raises(ValueError, match="single-token"):
        paged_decode_attention(q2, kp, kp, pt, lengths, n_heads=H)
    # h=4, d=16: below the 64-lane column-slice floor -> kernel refuses
    q_small = jnp.asarray(rng.normal(size=(B, 1, 64)), jnp.float32)
    kp_small = jnp.asarray(rng.normal(size=(4, 16, 64)), jnp.float32)
    with pytest.raises(ValueError, match="packable"):
        paged_decode_attention(q_small, kp_small, kp_small, pt, lengths,
                               n_heads=4, impl="kernel")
    out = paged_decode_attention(q_small, kp_small, kp_small, pt, lengths,
                                 n_heads=4)  # auto -> reference
    assert out.shape == (B, 1, 64)
