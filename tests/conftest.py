"""Test harness: 8 virtual CPU devices.

The reference has no tests at all (SURVEY §4); its README checklist
(init/teardown, wrapping, sampler wiring, rank-0 side effects, eval reduce)
is the invariant list these tests assert. Distribution is tested without a
cluster: XLA's host platform is forced to expose 8 devices, so the mesh,
GSPMD sharding, collectives, and ring attention all run on one CPU.

Two tiers (round 5):

    pytest -m fast      # <60 s: one small config per subsystem — the
                        # routine pre-commit gate (marker list: pytest.ini)
    pytest tests/       # everything: interpret-mode Pallas numerics pins,
                        # e2e fits, real 2-process rendezvous (~20 min on
                        # this image's single CPU core; the cost is in
                        # exactly the tests worth keeping)
"""

import os

# Belt: env vars (effective if jax not yet imported).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import json  # noqa: E402
import time  # noqa: E402

import jax  # noqa: E402

# Suspenders: pytest plugins may have imported jax already (before this
# conftest ran), so also override through the config system — effective any
# time before backend initialization.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # this jax build predates the jax_num_cpu_devices option — the
    # XLA_FLAGS belt above is the only device-count lever, and it works
    # as long as no plugin imported jax before this conftest ran
    pass

import pytest  # noqa: E402

# ------------------------------------------------------ tier-1 time ledger
# The tier-1 gate runs under a HARD 870 s `timeout` that truncates the
# suite silently — a run that creeps past the budget loses its tail
# tests without any failure saying so. Every run therefore keeps a
# per-test duration ledger (setup+call+teardown summed per nodeid):
# tests/test_zzz_t1_budget.py audits it in-run (z-named so the
# alphabetical order of `-p no:randomly` runs it LAST, when the ledger
# is complete), and sessionfinish writes it as JSON for
# tools/check_durations.py to audit offline.
T1_BUDGET_S = 870.0
_T1_LEDGER: dict = {}
_T1_START = time.monotonic()


def pytest_runtest_logreport(report):
    _T1_LEDGER[report.nodeid] = (
        _T1_LEDGER.get(report.nodeid, 0.0) + report.duration
    )


def pytest_sessionfinish(session):
    out = os.environ.get(
        "DDP_T1_DURATIONS_OUT", "/tmp/_t1_durations.json"
    )
    try:
        with open(out, "w") as f:
            json.dump({
                "markexpr": getattr(
                    session.config.option, "markexpr", "") or "",
                "wall_s": round(time.monotonic() - _T1_START, 3),
                "budget_s": T1_BUDGET_S,
                "tests": {
                    k: round(v, 4) for k, v in _T1_LEDGER.items()
                },
            }, f)
    except OSError:
        pass  # an unwritable /tmp must not fail the suite itself


@pytest.fixture(scope="session")
def t1_duration_ledger():
    """The live per-nodeid duration dict (see ledger comment above)."""
    return _T1_LEDGER


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def compile_guard():
    """Assert-no-new-compiles context manager over serving engines.

    Wraps the engines' jit-cache-size counters (SlotEngine/PagedEngine
    `compile_stats()`): any XLA compile inside the `with` block — a new
    prompt bucket, a leaked dynamic shape, a paged-table shape change —
    fails loudly with the before/after counter diff. The
    zero-recompiles-under-churn property every serving test pins, as a
    reusable fixture::

        with compile_guard(engine):
            ...  # arbitrary admit/step/release churn
    """
    from contextlib import contextmanager

    @contextmanager
    def guard(*engines):
        before = [e.compile_stats() for e in engines]
        yield
        after = [e.compile_stats() for e in engines]
        assert after == before, (
            f"new XLA compiles inside compile_guard: {before} -> {after}"
        )

    return guard


@pytest.fixture
def ephemeral_port():
    """OS-assigned localhost port, as a callable: `port = ephemeral_port()`.

    Shared by every `net`-marked test that needs a port BEFORE the
    server binds (worker RPC specs, telemetry endpoints). Binding to
    port 0 and releasing leaves a tiny reuse race — acceptable for
    tests on a loopback-only box, and servers that can bind 0 directly
    (frontdoor's default) should do that instead and read the bound
    port back."""
    import socket

    def alloc() -> int:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return alloc


@pytest.fixture(scope="session", autouse=True)
def _reap_fleet_workers():
    """No spawned worker process survives the session — and a leak is a
    FAILURE, not a silent cleanup. The fleet tests spawn real OS
    workers (serve/supervisor.py registers every child pid); a test
    that leaks one — especially a SIGSTOPped one, which would hang any
    naive wait — gets it SIGKILLed+reaped here, then the assert makes
    the leak loud. Lazy import: sessions that never touch serve/ pay
    one module lookup."""
    yield
    import sys

    sup = sys.modules.get("ddp_practice_tpu.serve.supervisor")
    if sup is None:
        return  # nothing that can spawn was ever imported
    leaked = sup.reap_all()
    assert not leaked, (
        f"fleet worker processes leaked by the suite (now killed): "
        f"{leaked}"
    )


@pytest.fixture(autouse=True)
def _reset_mesh_registry():
    """Tests that set the framework's current mesh (directly or via
    Trainer) must not leak it into later tests — sharding constraints
    consult this global."""
    yield
    from ddp_practice_tpu.parallel.ring import set_current_mesh

    set_current_mesh(None)
