"""Checkpoint tests: save + the restore path the reference lacks
(SURVEY §2.5 — torch.save only, no load), including resume-through-Trainer
and restore-onto-a-mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu import checkpoint as ckpt
from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import build_mesh, shard_state
from ddp_practice_tpu.train import create_state, make_optimizer
from ddp_practice_tpu.train.loop import Trainer


def _state():
    cfg = TrainConfig()
    model = create_model("convnet")
    tx = make_optimizer(cfg)
    return create_state(
        model, tx, rng=jax.random.PRNGKey(7), sample_input=jnp.zeros((1, 28, 28, 1))
    )


@pytest.mark.fast
def test_roundtrip(tmp_path):
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state, extra={"precision_policy": "bf16", "step": 0})
    assert ckpt.exists(d)
    restored = ckpt.restore(d, jax.eval_shape(lambda: state))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state), restored,
    )
    man = ckpt.latest_manifest(d)
    assert man["extra"]["precision_policy"] == "bf16"  # the "scaler slot"


def test_restore_rejects_shape_mismatch(tmp_path):
    """A config drift (e.g. generate.py --seq_len override) fails loudly at
    restore time, not deep inside flax."""
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state, extra={"step": 0})
    bad = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct((7,) + a.shape, a.dtype), state
    )
    with pytest.raises(ValueError, match="different model configuration"):
        ckpt.restore(d, bad)


def test_restore_onto_mesh(tmp_path, devices):
    """A checkpoint written anywhere restores sharded onto a mesh
    (single-chip -> pod portability)."""
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state)
    mesh = build_mesh(MeshConfig(data=8))
    shardings = shard_state(jax.eval_shape(lambda: state), mesh)
    restored = ckpt.restore(d, jax.eval_shape(lambda: state), shardings=shardings)
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert len(leaf.sharding.device_set) == 8
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        ),
        jax.device_get(state.params), jax.device_get(restored.params),
    )


def test_save_is_crash_safe_mid_write(tmp_path, monkeypatch):
    """A crash during save (after leaves, before manifest) must leave the
    previous checkpoint restorable — the property train/elastic.py's
    restart loop depends on (VERDICT weak #2)."""
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state, step=1)

    bumped = state.replace(step=state.step + 1) if hasattr(state, "replace") \
        else state
    import json as json_mod

    def torn_dump(*a, **k):
        raise RuntimeError("simulated crash mid-save")

    monkeypatch.setattr(json_mod, "dump", torn_dump)
    try:
        ckpt.save(d, bumped, step=2)
    except RuntimeError:
        pass
    monkeypatch.undo()

    # the torn step-2 attempt is invisible; step 1 still restores
    assert ckpt.all_steps(d) == [1]
    restored = ckpt.restore(d, jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(np.asarray(restored.step), np.asarray(state.step))

    # and a later save cleans the debris and becomes the new latest
    ckpt.save(d, bumped, step=2)
    assert ckpt.all_steps(d) == [1, 2]
    assert not [p for p in (tmp_path / "ck").iterdir()
                if p.name.startswith("tmp.")]


@pytest.mark.fast
def test_retention_keeps_last_k(tmp_path):
    state = _state()
    d = str(tmp_path / "ck")
    for s in range(1, 6):
        ckpt.save(d, state, step=s, keep_last=3)
    assert ckpt.all_steps(d) == [3, 4, 5]
    man = ckpt.latest_manifest(d)
    assert man["extra"]["step"] == 5


def test_restore_falls_back_past_truncated_manifest(tmp_path):
    """A manifest torn mid-write (exists but parse-fails — e.g. power
    loss after a rename of an older layout) must not strand the run:
    restore and latest_manifest fall back to the previous COMPLETE
    checkpoint instead of dying on the corrupt newest one."""
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state, step=1)
    import dataclasses

    bumped = dataclasses.replace(state, step=state.step + 1)
    ckpt.save(d, bumped, step=2)
    # truncate step-2's manifest mid-stream: present, but invalid JSON
    man2 = tmp_path / "ck" / "step_2" / "manifest.json"
    man2.write_bytes(man2.read_bytes()[: len(man2.read_bytes()) // 2])
    assert ckpt.exists(d)
    assert ckpt.latest_manifest(d)["extra"]["step"] == 1
    restored = ckpt.restore(d, jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(
        np.asarray(restored.step), np.asarray(state.step)
    )
    # an EMPTY manifest (0 bytes flushed) is the same failure class
    man2.write_bytes(b"")
    assert ckpt.latest_manifest(d)["extra"]["step"] == 1
    # and with every manifest corrupt there is no checkpoint — loud, not
    # a half-parsed resume
    man1 = tmp_path / "ck" / "step_1" / "manifest.json"
    man1.write_bytes(b'{"schema_version": 2, "paths": [')
    with pytest.raises(FileNotFoundError):
        ckpt.restore(d, jax.eval_shape(lambda: state))


def test_restore_ignores_torn_dir(tmp_path):
    """A directory from a crashed rename-less writer (leaves without
    manifest) is never selected."""
    state = _state()
    d = tmp_path / "ck"
    ckpt.save(str(d), state, step=3)
    torn = d / "step_9"
    torn.mkdir()
    (torn / "leaves.npz").write_bytes(b"garbage")
    assert ckpt.all_steps(str(d)) == [3]
    restored = ckpt.restore(str(d), jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(
        np.asarray(restored.step), np.asarray(state.step)
    )


def test_restore_falls_back_to_complete_aside_dir(tmp_path):
    """Crash window of a same-step re-save: only tmp./.old. copies exist
    (both complete — manifest is written last); restore must use the
    newest rather than strand the run with no checkpoint."""
    import os

    state = _state()
    d = tmp_path / "ck"
    ckpt.save(str(d), state, step=5)
    os.rename(d / "step_5", d / "step_5.old.999")  # simulate the window
    assert ckpt.exists(str(d))
    restored = ckpt.restore(str(d), jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(
        np.asarray(restored.step), np.asarray(state.step)
    )


def test_save_async_roundtrip(tmp_path):
    """Background-thread save publishes the same bytes as the sync path."""
    state = _state()
    d = str(tmp_path / "ck")
    handle = ckpt.save_async(d, state, extra={"step": 3}, step=3)
    path = handle.wait()
    assert path.endswith("step_3") and handle.done()
    restored = ckpt.restore(d, jax.eval_shape(lambda: state))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state), restored,
    )


def test_save_async_serialized_sequence(tmp_path):
    """Waited-on successive async saves retain every step (no debris sweep
    eats a live write) and restore picks the newest."""
    state = _state()
    d = str(tmp_path / "ck")
    for s in (1, 2, 3):
        ckpt.save_async(d, state, step=s).wait()
    assert ckpt.all_steps(d) == [1, 2, 3]
    assert ckpt.latest_manifest(d)["extra"]["step"] == 3


def test_trainer_periodic_step_checkpoints(tmp_path):
    """--ckpt_every N: mid-epoch checkpoints appear at step boundaries and
    resume restores the newest (lost work bounded by N steps, not an
    epoch)."""
    d = str(tmp_path / "ck")
    cfg = TrainConfig(
        dataset="synthetic",
        synthetic_size=256,
        epochs=1,
        batch_size=32,
        log_every_steps=0,
        checkpoint_dir=d,
        checkpoint_every_steps=3,
        mesh=MeshConfig(data=1),
    )
    t = Trainer(cfg)
    t.fit()
    total = int(t.state.step)  # 8 steps at bs 32 over 256 images
    steps = ckpt.all_steps(d)
    assert total in steps  # final save
    assert any(s in steps for s in (3, 6))  # a mid-epoch periodic save
    t2 = Trainer(cfg.replace(resume=True))
    assert int(t2.state.step) == total


@pytest.mark.slow  # >10s on the tier-1 box (pytest.ini: excluded from the gate)
def test_trainer_resume(tmp_path):
    """Train 1 epoch, checkpoint, resume: step counter continues — the
    resume path the reference never built."""
    d = str(tmp_path / "ck")
    cfg = TrainConfig(
        dataset="synthetic",
        epochs=1,
        batch_size=32,
        log_every_steps=0,
        checkpoint_dir=d,
        mesh=MeshConfig(data=1),
    )
    t1 = Trainer(cfg)
    t1.fit()
    steps_after_first = int(t1.state.step)
    assert steps_after_first > 0

    t2 = Trainer(cfg.replace(resume=True))
    assert int(t2.state.step) == steps_after_first
