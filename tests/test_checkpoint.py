"""Checkpoint tests: save + the restore path the reference lacks
(SURVEY §2.5 — torch.save only, no load), including resume-through-Trainer
and restore-onto-a-mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu import checkpoint as ckpt
from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import build_mesh, shard_state
from ddp_practice_tpu.train import create_state, make_optimizer
from ddp_practice_tpu.train.loop import Trainer


def _state():
    cfg = TrainConfig()
    model = create_model("convnet")
    tx = make_optimizer(cfg)
    return create_state(
        model, tx, rng=jax.random.PRNGKey(7), sample_input=jnp.zeros((1, 28, 28, 1))
    )


def test_roundtrip(tmp_path):
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state, extra={"precision_policy": "bf16", "step": 0})
    assert ckpt.exists(d)
    restored = ckpt.restore(d, jax.eval_shape(lambda: state))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state), restored,
    )
    man = ckpt.latest_manifest(d)
    assert man["extra"]["precision_policy"] == "bf16"  # the "scaler slot"


def test_restore_onto_mesh(tmp_path, devices):
    """A checkpoint written anywhere restores sharded onto a mesh
    (single-chip -> pod portability)."""
    state = _state()
    d = str(tmp_path / "ck")
    ckpt.save(d, state)
    mesh = build_mesh(MeshConfig(data=8))
    shardings = shard_state(jax.eval_shape(lambda: state), mesh)
    restored = ckpt.restore(d, jax.eval_shape(lambda: state), shardings=shardings)
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert len(leaf.sharding.device_set) == 8
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        ),
        jax.device_get(state.params), jax.device_get(restored.params),
    )


def test_trainer_resume(tmp_path):
    """Train 1 epoch, checkpoint, resume: step counter continues — the
    resume path the reference never built."""
    d = str(tmp_path / "ck")
    cfg = TrainConfig(
        dataset="synthetic",
        epochs=1,
        batch_size=32,
        log_every_steps=0,
        checkpoint_dir=d,
        mesh=MeshConfig(data=1),
    )
    t1 = Trainer(cfg)
    t1.fit()
    steps_after_first = int(t1.state.step)
    assert steps_after_first > 0

    t2 = Trainer(cfg.replace(resume=True))
    assert int(t2.state.step) == steps_after_first
