"""Streaming exactly-once contract (serve/router.py TokenStream),
host-pure.

The router's streaming plane is policy over the ReplicaHandle seam —
so it is pinned here against scripted fake replicas on a FakeClock,
with no engine and no jit: chunk splicing, the dedup cursor across a
mid-stream crash (resume marker, suppressed re-decode, zero consumer
duplicates/gaps), the error-retry resume edge, the typed end a shed
mid-stream must produce instead of silence, and the offline
check_stream audit over a pumped TelemetryExporter file — both ways
(the real run passes; a corrupted copy fails).

The real-engine end of the same contract (scheduler chunk emission,
worker pub frames, SIGKILL chaos) lives in tests/test_serve_scheduler
.py::test_stream_chunks_match_completions and tests/test_worker_stream
.py — this file is the fast tier-1 core.
"""

import json

import pytest

from ddp_practice_tpu.serve import (
    FakeClock,
    ReplicaCrashed,
    Request,
    Router,
    RouterConfig,
)
from ddp_practice_tpu.serve.scheduler import Completion, TokenChunk

VOCAB = 32


def oracle(prompt, n):
    """The fake fleet's greedy decode: a pure function of the prefix,
    like real greedy decoding — so a failover's re-decode of
    prompt+salvage reproduces the suffix exactly."""
    out = []
    cur = list(prompt)
    for _ in range(n):
        nxt = (sum(cur[-3:]) * 7 + len(cur)) % VOCAB
        out.append(nxt)
        cur.append(nxt)
    return out


class FakeReplica:
    """Scripted in-process replica implementing the ReplicaHandle seam:
    1 token per request per tick, one TokenChunk per tick (the burst),
    deterministic oracle decode. `crash_at` raises ReplicaCrashed on
    that step call; `salvage_lag` makes evacuate() return that many
    fewer tokens than the chunks already published — the survivor then
    RE-decodes tokens the consumer has seen, which the dedup cursor
    must suppress."""

    def __init__(self, rid, clock, *, slots=4, crash_at=None,
                 salvage_lag=0, error_rids=(), restartable=True):
        self.id = rid
        self.clock = clock
        self.slots = slots
        self.crash_at = crash_at
        self.salvage_lag = salvage_lag
        self.error_rids = set(error_rids)
        self.restartable = restartable
        self.health = None          # armed by Router.__init__
        self.running = {}           # rid -> {req, tokens, base}
        self.queue = []
        self.completions = []
        self.chunks = []
        self.consumed = 0
        self.chunks_consumed = 0
        self._chunk_seq = {}
        self.steps = 0

    # ---------------------------------------------------- the seam
    def submit(self, req):
        self.queue.append(req)

    def step(self):
        self.steps += 1
        if self.crash_at is not None and self.steps >= self.crash_at:
            self.crash_at = None
            raise ReplicaCrashed(f"scripted crash on replica {self.id}")
        while self.queue and len(self.running) < self.slots:
            req = self.queue.pop(0)
            self.running[req.rid] = {"req": req, "tokens": [],
                                     "done": req.max_new_tokens}
        self.clock.tick()
        for rid in list(self.running):
            st = self.running[rid]
            req = st["req"]
            prefix = list(req.prompt) + st["tokens"]
            tok = oracle(prefix, 1)[0]
            start = len(st["tokens"])
            st["tokens"].append(tok)
            if len(st["tokens"]) >= st["done"]:
                status = ("error" if rid in self.error_rids
                          else "length")
                if rid in self.error_rids:
                    self.error_rids.discard(rid)
                self._emit(rid, req.trace_id, start, [tok],
                           final=True, status=status)
                self.completions.append(Completion(
                    rid=rid, tokens=st["tokens"], status=status,
                    arrival=req.arrival, finish=self.clock.now(),
                    ttft=0.01, flight={"queue_s": 0.0, "prefill_s": 0.0,
                                       "decode_s": 0.01},
                    trace_id=req.trace_id,
                ))
                del self.running[rid]
            else:
                self._emit(rid, req.trace_id, start, [tok])

    def _emit(self, rid, trace_id, start, tokens, final=False,
              status=None):
        seq = self._chunk_seq.get(rid, 0)
        self._chunk_seq[rid] = seq + 1
        self.chunks.append(TokenChunk(
            rid=rid, trace_id=trace_id, seq=seq, start=start,
            tokens=tokens, t=self.clock.now(), final=final,
            status=status,
        ))
        if final:
            self._chunk_seq.pop(rid, None)

    def poll(self):
        new = self.completions[self.consumed:]
        self.consumed = len(self.completions)
        return new

    def poll_chunks(self):
        new = self.chunks[self.chunks_consumed:]
        self.chunks_consumed = len(self.chunks)
        return new

    def evacuate(self):
        out = []
        for rid, st in self.running.items():
            toks = st["tokens"]
            if self.salvage_lag:
                toks = toks[:max(0, len(toks) - self.salvage_lag)]
            out.append((st["req"], list(toks), None,
                        {"queue_s": 0.0, "prefill_s": 0.0,
                         "decode_s": 0.0}))
        for req in self.queue:  # queued work is harvested too
            out.append((req, [], None,
                        {"queue_s": 0.0, "prefill_s": 0.0,
                         "decode_s": 0.0}))
        self.running.clear()
        self.queue.clear()
        self._chunk_seq.clear()
        return out

    def shed_queued(self, min_priority):
        keep, shed = [], []
        for r in self.queue:
            (shed if r.priority >= min_priority else keep).append(r)
        self.queue = keep
        return [r.rid for r in shed]

    # ------------------------------------------------- observables
    @property
    def load(self):
        return len(self.queue) + len(self.running)

    @property
    def has_queue_space(self):
        return len(self.queue) < 64

    @property
    def max_slots(self):
        return self.slots

    @property
    def queue_len(self):
        return len(self.queue)

    @property
    def active(self):
        return len(self.running)

    def fits_prompt(self, n_tokens):
        return n_tokens <= 64

    # --------------------------------------------------- lifecycle
    def probe_ok(self, now):
        return self.restartable

    def restart(self):
        self.running.clear()
        self.queue.clear()
        self.steps = 0

    def warmup(self, widths=None):
        pass

    def compile_stats(self):
        return {}


def _mk_router(replica_factory, n=2, telemetry=None, **cfg_kw):
    clock = FakeClock(step_s=0.01)
    reps = [replica_factory(i, clock) for i in range(n)]
    cfg = RouterConfig(retry_jitter=0.0, probe_base_s=0.05,
                       retry_base_s=0.02, **cfg_kw)
    return Router(reps, clock=clock, config=cfg,
                  telemetry=telemetry), reps


def _submit_all(router, reqs):
    for r in reqs:
        router.submit(r)


def _reqs(n, max_new=6):
    return [Request(rid=i, prompt=[3 + i, 1, 4], max_new_tokens=max_new,
                    arrival=0.0) for i in range(n)]


def test_stream_happy_path_incremental_and_typed_end():
    """No faults: tokens arrive incrementally (more than one tokens
    event), seq is contiguous, the end is typed, and the stream's
    concatenation equals both the completion and the oracle."""
    router, _ = _mk_router(lambda i, c: FakeReplica(i, c))
    _submit_all(router, _reqs(3, max_new=6))
    comps = {c.rid: c for c in router.run_until_idle()}
    assert set(comps) == {0, 1, 2}
    for rid, c in comps.items():
        st = router.stream(rid)
        assert st is not None and st.closed
        assert st.status == c.status == "length"
        assert st.tokens() == c.tokens == oracle([3 + rid, 1, 4], 6)
        assert [ev.seq for ev in st.events] \
            == list(range(len(st.events)))
        kinds = [ev.kind for ev in st.events]
        assert kinds.count("end") == 1 and kinds[-1] == "end"
        # streaming means incremental: several tokens edges, not one
        # end-of-request lump
        assert kinds.count("tokens") >= 3
        assert st.suppressed == 0 and st.gaps == 0


def test_streaming_off_is_end_of_request_only():
    """The control arm: streaming=False drains replica chunks (handle
    state stays bounded) but exposes no streams."""
    router, reps = _mk_router(lambda i, c: FakeReplica(i, c),
                              streaming=False)
    _submit_all(router, _reqs(2, max_new=4))
    comps = router.run_until_idle()
    assert len(comps) == 2
    assert router.stream(0) is None and not router.streams
    # chunks were consumed off the replicas even with no stream
    for r in reps:
        assert r.chunks_consumed == len(r.chunks) > 0


def test_crash_mid_stream_resumes_exactly_once():
    """Replica 0 dies mid-decode with its salvage point BEHIND what it
    already streamed (salvage_lag=2): the survivor re-decodes tokens
    the consumer has seen. The consumer must observe: one resumed
    marker, the oracle's exact token sequence (no duplicate, no hole),
    contiguous seq, suppressed > 0 (the re-decode was absorbed by the
    cursor, not delivered)."""
    def factory(i, clock):
        return FakeReplica(i, clock,
                           crash_at=4 if i == 0 else None,
                           salvage_lag=2 if i == 0 else 0,
                           restartable=False)

    router, _ = _mk_router(factory)
    reqs = _reqs(4, max_new=8)
    _submit_all(router, reqs)
    comps = {c.rid: c for c in router.run_until_idle()}
    assert set(comps) == {0, 1, 2, 3}

    resumed_streams = 0
    suppressed_total = 0
    for rid, c in comps.items():
        st = router.stream(rid)
        want = oracle([3 + rid, 1, 4], 8)
        assert c.status == "length" and c.tokens == want
        # the consumer's spliced view is EXACTLY the fault-free decode
        assert st.tokens() == want
        assert st.closed and st.status == "length"
        assert [ev.seq for ev in st.events] \
            == list(range(len(st.events)))
        assert st.gaps == 0
        kinds = [ev.kind for ev in st.events]
        if "resumed" in kinds:
            resumed_streams += 1
            ev = st.events[kinds.index("resumed")]
            assert ev.attrs["reason"] == "failover"
            assert ev.attrs["from_replica"] == 0
            # resume stall is measured at the consumer
            assert st.resume_gap_s > 0.0
        suppressed_total += st.suppressed
    # the crash hit mid-decode with requests on replica 0
    assert resumed_streams >= 1
    # salvage_lag forced a re-decode of already-delivered tokens:
    # the cursor absorbed them
    assert suppressed_total > 0


def test_error_retry_marks_resume_and_dedups():
    """A replica 'error' completion (transient fault) retries on the
    fleet: the stream carries a reason=retry resume marker and the
    re-decode of the salvaged prefix never reaches the consumer."""
    def factory(i, clock):
        return FakeReplica(i, clock, error_rids={0} if i == 0 else ())

    router, _ = _mk_router(factory, max_retries=2)
    router.submit(Request(rid=0, prompt=[3, 1, 4], max_new_tokens=6,
                          arrival=0.0))
    comps = {c.rid: c for c in router.run_until_idle()}
    c = comps[0]
    st = router.stream(0)
    want = oracle([3, 1, 4], 6)
    assert c.status == "length" and c.tokens == want
    assert st.tokens() == want
    kinds = [ev.kind for ev in st.events]
    assert "resumed" in kinds
    ev = st.events[kinds.index("resumed")]
    assert ev.attrs["reason"] == "retry"
    assert st.gaps == 0


def test_shed_mid_stream_ends_typed_not_silent():
    """Every replica dies permanently mid-stream: the in-flight
    streams must terminate with a typed end (status shed/timeout) —
    a consumer waiting on the stream learns its fate, never hangs on
    silence."""
    def factory(i, clock):
        return FakeReplica(i, clock, crash_at=3, restartable=False)

    router, _ = _mk_router(factory)
    _submit_all(router, _reqs(3, max_new=10))
    comps = {c.rid: c for c in router.run_until_idle()}
    assert set(comps) == {0, 1, 2}
    for rid, c in comps.items():
        st = router.stream(rid)
        assert c.status == "shed"
        assert st.closed and st.status == "shed"
        assert st.events[-1].kind == "end"
        assert st.events[-1].status == "shed"


def test_rejected_at_door_still_ends_stream():
    router, _ = _mk_router(lambda i, c: FakeReplica(i, c))
    router.submit(Request(rid=9, prompt=[1], max_new_tokens=0,
                          arrival=0.0))
    st = router.stream(9)
    assert st.closed and st.status == "rejected"
    assert [ev.kind for ev in st.events] == ["end"]


def test_telemetry_chunk_lines_pass_check_stream_both_ways(tmp_path):
    """The JSONL the router writes under chaos IS the audit artifact:
    tools/check_stream.py must pass it verbatim and fail a corrupted
    copy (one duplicated delivery line)."""
    from ddp_practice_tpu.utils.telemetry import TelemetryExporter
    from tools.check_stream import (
        OK, VIOLATION, load_jsonl, main, stream_verdict,
    )

    path = str(tmp_path / "run.jsonl")
    exp = TelemetryExporter(path, clock=lambda: 0.0, start=False)

    def factory(i, clock):
        return FakeReplica(i, clock,
                           crash_at=4 if i == 0 else None,
                           salvage_lag=1 if i == 0 else 0,
                           restartable=False)

    clock = FakeClock(step_s=0.01)
    reps = [factory(i, clock) for i in range(2)]
    router = Router(reps, clock=clock,
                    config=RouterConfig(retry_jitter=0.0),
                    telemetry=exp)
    _submit_all(router, _reqs(4, max_new=8))
    router.run_until_idle()
    exp.pump()
    exp.close()

    lines = load_jsonl(path)
    ok, report = stream_verdict(lines)
    assert ok, report
    assert report["streams"] == 4
    # resumed markers are part of the PASSING record, not a violation
    assert any(ln.get("event") == "resumed" for ln in lines)
    assert main([path]) == OK

    # corrupt: replay one token-carrying chunk line (a duplicate
    # delivery) — the audit must catch it
    bad = tmp_path / "bad.jsonl"
    out, dup = [], None
    for ln in lines:
        out.append(json.dumps(ln))
        if (dup is None and ln.get("kind") == "chunk"
                and ln.get("event") == "tokens" and ln.get("n")):
            dup = json.dumps(ln)
            out.append(dup)
    assert dup is not None
    bad.write_text("\n".join(out) + "\n")
    assert main([str(bad)]) == VIOLATION
