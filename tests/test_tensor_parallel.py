"""Tensor-parallel tests: TP-sharded ViT training matches replicated ViT.

Model parallelism is absent from the reference (SURVEY §2.3); the mesh
design carries it from day one. GSPMD turns PartitionSpecs on QKV/MLP
parameters into Megatron-style column/row-parallel execution — these tests
pin the numerics to the replicated baseline.
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from ddp_practice_tpu.config import MeshConfig, TrainConfig
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.train import create_state, make_optimizer, make_train_step


def _setup(mesh_cfg, rules=None, devices=None):
    cfg = TrainConfig(optimizer="sgd", learning_rate=1e-2)
    mesh = build_mesh(mesh_cfg, devices=devices)
    model = create_model("vit_tiny", depth=2, hidden_dim=32, num_heads=4, mlp_dim=64)
    tx = make_optimizer(cfg)
    sample = jnp.zeros((1, 16, 16, 3))

    def init_fn(r):
        return create_state(model, tx, rng=r, sample_input=sample)

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, rules)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    bsh = batch_sharding(mesh)
    step = make_train_step(
        model, tx, mesh=mesh, state_shardings=shardings, batch_shardings=bsh
    )
    return mesh, state, step, bsh


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.uniform(size=(n, 16, 16, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, n), jnp.int32),
        "weight": jnp.ones((n,), jnp.float32),
    }


@pytest.mark.fast
def test_tp_sharding_rules_applied(devices):
    rules = param_sharding_rules("vit_tiny")
    mesh, state, _, _ = _setup(MeshConfig(data=2, tensor=4), rules)
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    spec = qkv.sharding.spec
    assert "tensor" in str(spec), spec
    # sharded leaf really is split across devices
    assert qkv.addressable_shards[0].data.shape[2] == qkv.shape[2] // 4


def test_tp_matches_replicated(devices):
    batch = _batch(8, seed=4)
    rules = param_sharding_rules("vit_tiny")

    mesh_r, state_r, step_r, bsh_r = _setup(
        MeshConfig(data=1), devices=jax.devices()[:1]
    )
    mesh_t, state_t, step_t, bsh_t = _setup(MeshConfig(data=2, tensor=4), rules)

    # pin EXECUTION, not sharded-init RNG: this image's old jax draws
    # different random bits for row-parallel kernels when init is jitted
    # with TP out_shardings (threefry not partition-invariant there), so
    # start both runs from the replicated init resharded into the TP
    # layout — the Megatron column/row-parallel math is what's under test
    state_t = state_t.replace(params=jax.tree.map(
        lambda r, t: jax.device_put(np.asarray(r), t.sharding),
        jax.device_get(state_r.params), state_t.params,
    ))

    br = {k: jax.device_put(v, bsh_r) for k, v in batch.items()}
    bt = {k: jax.device_put(v, bsh_t) for k, v in batch.items()}
    for _ in range(2):
        state_r, mr = step_r(state_r, br)
        state_t, mt = step_t(state_t, bt)
    np.testing.assert_allclose(
        float(mr["loss"]), float(mt["loss"]), rtol=2e-4
    )
    pr = jax.device_get(state_r.params)
    pt = jax.device_get(state_t.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5),
        pr, pt,
    )
