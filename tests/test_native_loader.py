"""Native C++ loader backend: bit-identity with the numpy path.

The native backend only accelerates batch assembly; epoch order comes from
the same NumPy permutation either way, so the two backends must produce
identical batches.
"""

import numpy as np
import pytest

from ddp_practice_tpu.data import DataLoader
from ddp_practice_tpu.data.datasets import synthetic_image_classification
from ddp_practice_tpu.data import native_loader


pytestmark = pytest.mark.skipif(
    not native_loader.available(), reason="native loader not built (no g++?)"
)


def _ds():
    return synthetic_image_classification(
        n=512, image_shape=(8, 8, 1), num_classes=5, seed=11
    )


@pytest.mark.fast
def test_native_gather_matches_numpy():
    ds = _ds()
    gather = native_loader.make_gather(ds)
    rng = np.random.default_rng(0)
    idx = rng.integers(0, len(ds), 200)
    imgs_n, lbls_n = gather(idx)
    np.testing.assert_array_equal(imgs_n, ds.images[idx])
    np.testing.assert_array_equal(lbls_n, ds.labels[idx])


def test_loader_backends_bit_identical():
    ds = _ds()
    kw = dict(global_batch_size=64, seed=9, shuffle=True)
    py = DataLoader(ds, backend="python", **kw)
    nat = DataLoader(ds, backend="native", **kw)
    for epoch in range(2):
        py.set_epoch(epoch)
        nat.set_epoch(epoch)
        for a, b in zip(py, nat):
            np.testing.assert_array_equal(a["image"], b["image"])
            np.testing.assert_array_equal(a["label"], b["label"])
            np.testing.assert_array_equal(a["weight"], b["weight"])


def test_large_batch_multithreaded_path():
    ds = _ds()
    gather = native_loader.make_gather(ds)
    idx = np.tile(np.arange(512), 8)  # 4096 rows -> threads engage
    imgs, lbls = gather(idx)
    np.testing.assert_array_equal(imgs, ds.images[idx])
    np.testing.assert_array_equal(lbls, ds.labels[idx])
