"""One-shot / serve equivalence: the cache pool is an optimization,
not an approximation.

Greedy decode through the serving path (bucketed prefill-admit + batched
single-token steps, serve/engine.py) must produce TOKEN-IDENTICAL output
to the one-shot `make_generate_fn` scan for the same (params, prompt) —
both paths are thin clients of `inference.decode_apply`, and the
left-alignment shift is invisible to RoPE. Pinned for single requests,
a mid-decode join, and a left-padded variable-length batch driven
through `pad_left_prompts` (the layout serve admission generalizes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.inference import make_generate_fn, pad_left_prompts
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, SlotEngine

# every test here compiles BOTH the one-shot scan and the serve programs
# (~15-25 s each on the CI CPU) — full-suite tier only, per the tier-1
# 870 s budget (pytest.ini)
pytestmark = pytest.mark.slow

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _serve_greedy(lm, prompts, n_new, max_slots=4):
    """Run prompts through the engine concurrently; per-request tokens."""
    model, params = lm
    eng = SlotEngine(model, params, EngineConfig(
        max_slots=max_slots, max_len=128, prompt_buckets=(8,),
    ))
    slots = [eng.admit(p) for p in prompts]
    out = [[] for _ in prompts]
    for _ in range(n_new):
        toks = eng.step()
        for i, s in enumerate(slots):
            out[i].append(int(toks[s]))
    return out


def test_single_request_matches_one_shot(devices, lm):
    model, params = lm
    prompt = [3, 1, 4, 1, 5]
    n = 10
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    want = np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))
    got = _serve_greedy(lm, [prompt], n)[0]
    assert got == want[0, len(prompt):].tolist()


def test_batched_requests_match_their_own_one_shot_runs(devices, lm):
    """Batch-mates must not bleed into each other: every request's serve
    tokens equal its SOLO one-shot run."""
    model, params = lm
    prompts = [[3, 1, 4], [2, 7, 1, 8, 2], [5], [6, 6]]
    n = 8
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    got = _serve_greedy(lm, prompts, n)
    for p, g in zip(prompts, got):
        want = np.asarray(gen(params, jnp.asarray([p], jnp.int32)))
        assert g == want[0, len(p):].tolist()


def test_mid_decode_join_matches_one_shot(devices, lm):
    """A request admitted while another is mid-generation gets exactly
    its solo tokens — continuous batching is transparent to clients."""
    model, params = lm
    eng = SlotEngine(model, params, EngineConfig(
        max_slots=2, max_len=128, prompt_buckets=(8,),
    ))
    s1 = eng.admit([3, 1, 4, 1, 5])
    for _ in range(4):
        eng.step()
    p2 = [2, 7, 1, 8]
    s2 = eng.admit(p2)
    got = [int(eng.step()[s2]) for _ in range(6)]
    gen = jax.jit(make_generate_fn(model, max_new_tokens=6, temperature=0.0))
    want = np.asarray(gen(params, jnp.asarray([p2], jnp.int32)))
    assert got == want[0, len(p2):].tolist()


def test_left_padded_batch_matches_one_shot_path(devices, lm):
    """The pad_left_prompts one-shot batch (variable lengths, attn_start)
    and the serve path agree token-for-token — same layout, same mask,
    same decode_apply."""
    model, params = lm
    prompts = [[3, 1, 4], [2, 7, 1, 8, 2], [5]]
    tokens, lens = pad_left_prompts(prompts)
    n = 6
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    want = np.asarray(gen(params, tokens, None, lens))
    width = tokens.shape[1]
    got = _serve_greedy(lm, prompts, n)
    for i in range(len(prompts)):
        assert got[i] == want[i, width:].tolist()


def test_sampled_serve_is_deterministic_per_request(devices, lm):
    """Sampling runs per-slot key chains: a request's tokens depend on
    its own seed, not on batch composition — the same request sampled
    alone and next to a neighbor yields identical tokens."""
    model, params = lm
    cfg = dict(max_len=128, prompt_buckets=(8,), temperature=1.3, top_k=8)
    prompt = [7, 7, 7]

    eng_solo = SlotEngine(model, params, EngineConfig(max_slots=2, **cfg))
    s = eng_solo.admit(prompt, seed=42)
    solo = [int(eng_solo.step()[s]) for _ in range(8)]

    eng_pair = SlotEngine(model, params, EngineConfig(max_slots=2, **cfg))
    eng_pair.admit([1, 2, 3, 4], seed=7)   # different slot, different seed
    s2 = eng_pair.admit(prompt, seed=42)
    paired = [int(eng_pair.step()[s2]) for _ in range(8)]

    # the key chain is the request's seed, not its slot: placement and
    # batch-mates don't change the sample stream
    assert solo == paired
    assert all(0 <= t < VOCAB for t in solo)
