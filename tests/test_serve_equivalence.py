"""One-shot / serve equivalence: the cache pool is an optimization,
not an approximation.

Greedy decode through the serving path (bucketed prefill-admit + batched
single-token steps, serve/engine.py) must produce TOKEN-IDENTICAL output
to the one-shot `make_generate_fn` scan for the same (params, prompt) —
both paths are thin clients of `inference.decode_apply`, and the
left-alignment shift is invisible to RoPE. Pinned for single requests,
a mid-decode join, and a left-padded variable-length batch driven
through `pad_left_prompts` (the layout serve admission generalizes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_practice_tpu.inference import make_generate_fn, pad_left_prompts
from ddp_practice_tpu.models import create_model
from ddp_practice_tpu.serve import EngineConfig, PagedEngine, SlotEngine
from ddp_practice_tpu.serve.scheduler import FakeClock, Request, Scheduler

# every test here compiles BOTH the one-shot scan and the serve programs
# (~15-25 s each on the CI CPU) — full-suite tier only, per the tier-1
# 870 s budget (pytest.ini)
pytestmark = pytest.mark.slow

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        "lm_tiny", vocab_size=VOCAB, max_len=128, hidden_dim=64,
        depth=2, num_heads=4, mlp_dim=128, pos_emb="rope",
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _serve_greedy(lm, prompts, n_new, max_slots=4):
    """Run prompts through the engine concurrently; per-request tokens."""
    model, params = lm
    eng = SlotEngine(model, params, EngineConfig(
        max_slots=max_slots, max_len=128, prompt_buckets=(8,),
    ))
    slots = [eng.admit(p) for p in prompts]
    out = [[] for _ in prompts]
    for _ in range(n_new):
        toks = eng.step()
        for i, s in enumerate(slots):
            out[i].append(int(toks[s]))
    return out


def test_single_request_matches_one_shot(devices, lm):
    model, params = lm
    prompt = [3, 1, 4, 1, 5]
    n = 10
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    want = np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))
    got = _serve_greedy(lm, [prompt], n)[0]
    assert got == want[0, len(prompt):].tolist()


def test_batched_requests_match_their_own_one_shot_runs(devices, lm):
    """Batch-mates must not bleed into each other: every request's serve
    tokens equal its SOLO one-shot run."""
    model, params = lm
    prompts = [[3, 1, 4], [2, 7, 1, 8, 2], [5], [6, 6]]
    n = 8
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    got = _serve_greedy(lm, prompts, n)
    for p, g in zip(prompts, got):
        want = np.asarray(gen(params, jnp.asarray([p], jnp.int32)))
        assert g == want[0, len(p):].tolist()


def test_mid_decode_join_matches_one_shot(devices, lm):
    """A request admitted while another is mid-generation gets exactly
    its solo tokens — continuous batching is transparent to clients."""
    model, params = lm
    eng = SlotEngine(model, params, EngineConfig(
        max_slots=2, max_len=128, prompt_buckets=(8,),
    ))
    s1 = eng.admit([3, 1, 4, 1, 5])
    for _ in range(4):
        eng.step()
    p2 = [2, 7, 1, 8]
    s2 = eng.admit(p2)
    got = [int(eng.step()[s2]) for _ in range(6)]
    gen = jax.jit(make_generate_fn(model, max_new_tokens=6, temperature=0.0))
    want = np.asarray(gen(params, jnp.asarray([p2], jnp.int32)))
    assert got == want[0, len(p2):].tolist()


def test_left_padded_batch_matches_one_shot_path(devices, lm):
    """The pad_left_prompts one-shot batch (variable lengths, attn_start)
    and the serve path agree token-for-token — same layout, same mask,
    same decode_apply."""
    model, params = lm
    prompts = [[3, 1, 4], [2, 7, 1, 8, 2], [5]]
    tokens, lens = pad_left_prompts(prompts)
    n = 6
    gen = jax.jit(make_generate_fn(model, max_new_tokens=n, temperature=0.0))
    want = np.asarray(gen(params, tokens, None, lens))
    width = tokens.shape[1]
    got = _serve_greedy(lm, prompts, n)
    for i in range(len(prompts)):
        assert got[i] == want[i, width:].tolist()


def test_sampled_serve_is_deterministic_per_request(devices, lm):
    """Sampling runs per-slot key chains: a request's tokens depend on
    its own seed, not on batch composition — the same request sampled
    alone and next to a neighbor yields identical tokens."""
    model, params = lm
    cfg = dict(max_len=128, prompt_buckets=(8,), temperature=1.3, top_k=8)
    prompt = [7, 7, 7]

    eng_solo = SlotEngine(model, params, EngineConfig(max_slots=2, **cfg))
    s = eng_solo.admit(prompt, seed=42)
    solo = [int(eng_solo.step()[s]) for _ in range(8)]

    eng_pair = SlotEngine(model, params, EngineConfig(max_slots=2, **cfg))
    eng_pair.admit([1, 2, 3, 4], seed=7)   # different slot, different seed
    s2 = eng_pair.admit(prompt, seed=42)
    paired = [int(eng_pair.step()[s2]) for _ in range(8)]

    # the key chain is the request's seed, not its slot: placement and
    # batch-mates don't change the sample stream
    assert solo == paired
    assert all(0 <= t < VOCAB for t in solo)


# ----------------------------------------------------------------- paged
# The paged engine (serve/kv_pages.py, PagedEngine) must be just as
# invisible an optimization as the slot pool: same decode_apply, same
# sample_logits, per-slot positions instead of a shared cursor — greedy
# tokens identical per request, whatever the memory layout underneath.


def _tolerate_load_flake(attempt, args_per_try):
    """Cross-IMPLEMENTATION greedy identity (flat masked attention vs
    paged gather) compares two mathematically-equal but floating-point-
    different programs: a near-tied argmax can flip between PROCESS-level
    runs on this image's XLA CPU (thread-partitioning float
    nondeterminism under load — the same machine flakiness documented in
    CHANGES.md for the elastic segfault). One retry separates that
    transient from a real divergence bug, which fails every attempt."""
    for i, args in enumerate(args_per_try):
        try:
            return attempt(*args)
        except AssertionError:
            if i == len(args_per_try) - 1:
                raise


def _run_trace(engine, trace):
    """Drive one shared request trace through a Scheduler; tokens by rid."""
    sched = Scheduler(engine, clock=FakeClock(), max_queue=len(trace))
    for t in trace:
        sched.submit(Request(**t))
    sched.run_until_idle()
    return {c.rid: (c.status, c.tokens) for c in sched.completions}


def _shared_trace(rng, n=10):
    return [
        {
            "rid": i,
            "prompt": rng.integers(0, VOCAB, int(rng.integers(1, 9))).tolist(),
            "max_new_tokens": int(rng.integers(2, 16)),
        }
        for i in range(n)
    ]


def test_paged_engine_matches_slot_engine_on_shared_trace(
        devices, lm, compile_guard):
    """Greedy token-identity paged-vs-slot on one trace driven through
    both schedulers — churn, queueing, block growth, slot reuse and all.
    Both engines stay at two compiled programs throughout (pinned via
    the conftest compile_guard)."""
    model, params = lm

    def attempt(trace_seed):
        trace = _shared_trace(np.random.default_rng(trace_seed))
        slot_eng = SlotEngine(model, params, EngineConfig(
            max_slots=3, max_len=128, prompt_buckets=(8,), eos_id=5,
        ))
        paged_eng = PagedEngine(model, params, EngineConfig(
            max_slots=3, prompt_buckets=(8,), eos_id=5,
            block_size=8, max_blocks_per_slot=3,  # span 24 << slot's 128
        ))
        # warmup: one admit per bucket + one step each, then the trace
        # runs compile-free on both layouts
        for eng in (slot_eng, paged_eng):
            s = eng.admit([1, 2, 3], max_positions=8)
            eng.step()
            eng.release(s)
        slot_eng.reset_epoch()
        with compile_guard(slot_eng, paged_eng):
            got_slot = _run_trace(slot_eng, trace)
            got_paged = _run_trace(paged_eng, trace)
        assert got_paged == got_slot
        assert any(status == "eos" for status, _ in got_slot.values())

    # retry the SAME trace: a deterministic divergence must fail both
    # attempts; only a load transient passes the replay
    _tolerate_load_flake(attempt, [(11,), (11,)])


def _shared_prefix_trace(rng, prefixes, n=12):
    """K system prompts x many continuations — the PR-6 workload: every
    request is prefix + a short unique tail."""
    out = []
    for i in range(n):
        pre = prefixes[int(rng.integers(0, len(prefixes)))]
        tail = rng.integers(0, VOCAB, int(rng.integers(1, 5))).tolist()
        out.append({
            "rid": i,
            "prompt": list(pre) + tail,
            "max_new_tokens": int(rng.integers(8, 17)),
        })
    return out


def test_prefix_sharing_engine_matches_plain_paged_on_shared_trace(
        devices, lm, compile_guard):
    """THE PR-6 acceptance pin: greedy token-identity of the
    prefix-sharing engine (radix cache + CoW + block-aware preemption
    on an UNDERSIZED pool, so preemptions actually fire) vs the plain
    PagedEngine on the same shared-prefix trace — and zero new compiles
    once the suffix buckets are warm."""
    model, params = lm

    def attempt(trace_seed):
        rng = np.random.default_rng(trace_seed)
        prefixes = [rng.integers(0, VOCAB, 8).tolist() for _ in range(2)]
        trace = _shared_prefix_trace(rng, prefixes)
        plain = PagedEngine(model, params, EngineConfig(
            max_slots=3, prompt_buckets=(8, 16), eos_id=5,
            block_size=8, max_blocks_per_slot=4,
        ))
        shared = PagedEngine(model, params, EngineConfig(
            max_slots=3, prompt_buckets=(8, 16), eos_id=5,
            block_size=8, max_blocks_per_slot=4,
            # undersized pool: 6 real blocks for 3 slots x 4 — growth
            # must preempt, and preempted requests must still finish
            # token-identical via the scheduler's readmission path
            num_blocks=7, prefix_cache=True,
        ))
        # warm both engines' buckets (plain: scratch prefill; shared:
        # cold-miss + suffix-hit widths), one fork for the CoW program
        for eng in (plain, shared):
            for w in ((1, 9) if eng is plain else (1, 9)):
                s = eng.admit(list(range(1, w + 1)), max_positions=8)
                eng.step()
                eng.release(s)
        s = shared.admit(prefixes[0] + [1, 2], max_positions=8)
        f = shared.fork(s, seed=1)
        shared.step()
        shared.release(s)
        shared.release(f)
        shared.radix.clear()
        shared.radix.hit_tokens = shared.radix.miss_tokens = 0
        with compile_guard(plain, shared):
            got_plain = _run_trace(plain, trace)
            got_shared = _run_trace(shared, trace)
        assert got_shared == got_plain
        # the run really exercised the machinery it claims to pin
        assert shared.radix.hit_tokens > 0
        assert shared.preemptions > 0
        assert shared.blocks.num_used == len(shared.radix)  # slots drained

    # several independent traces, pass on the first identical one: this
    # untrained model's argmax gaps go below the ~1e-6 cross-path float
    # delta often enough that any SINGLE trace can flip a token with
    # the process's thread partitioning (the documented XLA-CPU class
    # above) — but a real sharing/CoW/preemption bug corrupts K/V and
    # diverges catastrophically on EVERY trace, failing all four
    _tolerate_load_flake(attempt, [(16,), (18,), (1,), (2,)])


def test_prefix_hit_serves_prompt_longer_than_every_bucket(devices, lm):
    """A prompt that outgrows every bucket is UNSERVABLE cold but
    admissible once its prefix is cached: the gate probes the radix
    tree and buckets only the suffix — long shared system prompts ride
    the cache through admission."""
    model, params = lm
    eng = PagedEngine(model, params, EngineConfig(
        max_slots=2, prompt_buckets=(8, 16),
        block_size=8, max_blocks_per_slot=5, prefix_cache=True,
    ))
    system = list(np.random.default_rng(3).integers(0, VOCAB, 16))
    long_prompt = [int(t) for t in system] + [7, 7, 7]   # 19 > bucket 16
    assert eng.admit_gate(len(long_prompt), 8,
                          prompt=long_prompt) == "never"
    # serve the bare system prompt once: its 2 full blocks get cached
    s = eng.admit([int(t) for t in system], max_positions=8)
    eng.step()
    eng.release(s)
    assert eng.admit_gate(len(long_prompt), 8, prompt=long_prompt) == "ok"
    sched = Scheduler(eng, clock=FakeClock())
    sched.submit(Request(rid=0, prompt=long_prompt, max_new_tokens=6))
    (c,) = sched.run_until_idle()
    assert c.status == "length" and len(c.tokens) == 6
    assert eng.radix.hit_tokens >= 16


def test_paged_request_outgrows_slot_engine_max_len(devices, lm):
    """A context the slot engine can NEVER serve (prompt + new tokens
    past its max_len ceiling) completes on the paged engine, and its
    prefix is greedy-identical to the one-shot run over the window the
    one-shot can reach."""
    model, params = lm   # model.max_len = 128
    prompt = [3, 1, 4, 1, 5]
    n_new = 150          # 8 + 150 > 128: beyond the model's own window
    slot_eng = SlotEngine(model, params, EngineConfig(
        max_slots=1, max_len=128, prompt_buckets=(8,),
    ))
    assert slot_eng.admit_gate(len(prompt), n_new) == "never"

    def attempt():
        paged_eng = PagedEngine(model, params, EngineConfig(
            max_slots=1, prompt_buckets=(8,), block_size=16,
            max_blocks_per_slot=10,          # cap 160 > model.max_len
        ))
        assert paged_eng.admit_gate(len(prompt), n_new) == "ok"
        sched = Scheduler(paged_eng, clock=FakeClock())
        sched.submit(Request(rid=0, prompt=prompt, max_new_tokens=n_new))
        (c,) = sched.run_until_idle()
        assert c.status == "length" and len(c.tokens) == n_new
        assert all(0 <= t < VOCAB for t in c.tokens)
        # prefix check against the longest one-shot run the window fits
        n_ref = 100
        gen = jax.jit(make_generate_fn(model, max_new_tokens=n_ref,
                                       temperature=0.0))
        want = np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))
        assert c.tokens[:n_ref] == want[0, len(prompt):].tolist()

    _tolerate_load_flake(attempt, [(), ()])
