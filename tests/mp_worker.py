"""Worker for the real multi-process tests (tests/test_multiprocess.py).

Runs as 2 actual OS processes that rendezvous through
`jax.distributed.initialize` on the CPU platform (2 local devices each, 4
global) — the torchrun-equivalent contract of the reference
(ddp_main_torchrun.py:102-104): every process calls the collectives, only
process 0 performs side effects. Exercises the code paths no single-process
test can reach:

- `jax.distributed.initialize` with an explicit coordinator
  (parallel/dist.py),
- the per-process `ShardSpec` local slice feeding
  `jax.make_array_from_process_local_data` (data/loader.py `_to_global`
  multi-process branch),
- `assert_in_sync`'s allgather branch, both agreeing and firing on a
  mismatch (train/elastic.py),
- process-0-only checkpoint writes with the collective leaf gather for
  multi-host-sharded (FSDP) state and the post-save barrier
  (checkpoint/__init__.py).

Prints ALL_OK as the last line on success; any assertion kills the exit
code, which the parent test asserts on.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    coord, nproc_s, pid_s, workdir = sys.argv[1:5]
    nproc, pid = int(nproc_s), int(pid_s)

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        # this jax build predates the jax_num_cpu_devices option (same
        # guard as tests/conftest.py) — fall back to the XLA flag, which
        # works because no device has been touched yet in this process
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()

    import numpy as np
    from jax.experimental import multihost_utils

    from ddp_practice_tpu.parallel import dist

    dist.initialize(coord, nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.process_index() == pid
    assert jax.device_count() == 2 * nproc, jax.device_count()
    print(f"[{pid}] distributed up: {jax.device_count()} global devices")

    # probe one tiny cross-process collective before the real scenarios:
    # some jax builds (e.g. this image's 0.4.37) rendezvous fine but then
    # refuse every multi-process computation on the CPU backend — that is
    # an environment limit, not a code bug, so report it distinctly (rc
    # 77) and let the parent test skip instead of fail
    try:
        multihost_utils.process_allgather(np.zeros(1))
    except Exception as e:
        if "Multiprocess computations aren't implemented" in str(e):
            print(f"[{pid}] MULTIPROCESS_CPU_UNSUPPORTED: {e}")
            return 77
        raise

    # --- assert_in_sync agreeing fingerprints: passes on every process ---
    from ddp_practice_tpu.train.elastic import assert_in_sync

    assert_in_sync(4242, what="mp test")
    print(f"[{pid}] sync-match ok")

    # --- sharded input pipeline: ShardSpec slice -> global jax.Array ---
    from ddp_practice_tpu.config import MeshConfig
    from ddp_practice_tpu.data import DataLoader, ShardSpec
    from ddp_practice_tpu.data.datasets import synthetic_image_classification
    from ddp_practice_tpu.data.loader import prefetch_to_device
    from ddp_practice_tpu.data.sharding import epoch_indices
    from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh

    ds = synthetic_image_classification(
        n=64, image_shape=(8, 8, 1), num_classes=10, seed=7
    )
    gbs = 16
    loader = DataLoader(
        ds, global_batch_size=gbs,
        shard=ShardSpec(dist.process_index(), dist.process_count()),
        seed=3407, shuffle=True,
    )
    loader.set_epoch(1)
    mesh = build_mesh(MeshConfig(data=-1))
    bsh = batch_sharding(mesh)
    # expected global order is host-computable on every process (same seed)
    order = epoch_indices(64, seed=3407, epoch=1, shuffle=True)
    it = prefetch_to_device(iter(loader), bsh, size=2)
    try:
        for step, batch in enumerate(it):
            assert batch["label"].shape[0] == gbs  # global shape
            assert not batch["label"].is_fully_addressable  # spans processes
            got = multihost_utils.process_allgather(batch["label"], tiled=True)
            want = ds.labels[order[step * gbs:(step + 1) * gbs]]
            np.testing.assert_array_equal(np.asarray(got), want)
    finally:
        it.close()
    print(f"[{pid}] sharded loader ok")

    # --- 2-process training with process-0-only checkpoint writes ---
    from ddp_practice_tpu.config import TrainConfig
    from ddp_practice_tpu.train.loop import Trainer

    ck = os.path.join(workdir, "ck")
    cfg = TrainConfig(
        model="convnet",
        dataset="synthetic",
        batch_size=8,  # per replica x 4 devices = 32 global
        epochs=1,
        max_steps_per_epoch=4,
        optimizer="adam",
        learning_rate=1e-3,
        log_every_steps=0,
        checkpoint_dir=ck,
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        mesh=MeshConfig(data=-1),
    )
    trainer = Trainer(cfg)
    summary = trainer.fit()
    assert summary["steps"] == 4, summary
    # every process sees the checkpoint (shared FS); the save barrier
    # guarantees it is complete before any process returns
    from ddp_practice_tpu import checkpoint as ckpt

    assert ckpt.exists(ck)
    man = ckpt.latest_manifest(ck)
    assert man["extra"]["step"] == 4, man
    # replicated params identical across processes after synced training
    leaf = jax.tree_util.tree_leaves(trainer.state.params)[0]
    host_leaf = np.asarray(jax.device_get(leaf)).ravel()[:8]
    gathered = multihost_utils.process_allgather(host_leaf)
    np.testing.assert_allclose(gathered[0], gathered[1], rtol=0, atol=0)
    print(f"[{pid}] train + process-0 checkpoint ok")

    # --- FSDP-sharded state: per-process shard writes (NO full gather) ---
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.fsdp import fsdp_rules
    from ddp_practice_tpu.parallel.mesh import shard_state
    from ddp_practice_tpu.train import create_state, make_optimizer

    import jax.numpy as jnp

    model = create_model("convnet")
    tx = make_optimizer(TrainConfig())

    def init_fn(r):
        return create_state(
            model, tx, rng=r, sample_input=jnp.zeros((4, 28, 28, 1))
        )

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = shard_state(
        abstract, mesh, fsdp_rules(2 * nproc, None, min_leaf_size=64)
    )
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    big = [
        leaf for leaf in jax.tree_util.tree_leaves(state.params)
        if leaf.size >= 64
    ]
    assert any(not leaf.is_fully_addressable for leaf in big), \
        "expected some FSDP leaves to span processes"
    ck2 = os.path.join(workdir, "ck_fsdp")
    ckpt.save(ck2, state, step=1)  # collective: all processes call
    # per-process shard files on disk, manifest records the sharded leaves
    step_dir = os.path.join(ck2, "step_1")
    for p in range(nproc):
        assert os.path.exists(
            os.path.join(step_dir, f"shards.{p}.npz")
        ), f"missing shard file for process {p}"
    import json as _json

    with open(os.path.join(step_dir, "manifest.json")) as f:
        man2 = _json.load(f)
    assert man2.get("sharded_leaves"), "manifest lists no sharded leaves"
    restored = ckpt.restore(ck2, abstract)
    ref = multihost_utils.process_allgather(big[0], tiled=True)
    leaves = jax.tree_util.tree_leaves(state.params)
    big_idx = next(i for i, l in enumerate(leaves) if l is big[0])
    got = np.asarray(jax.tree_util.tree_leaves(restored.params)[big_idx])
    np.testing.assert_allclose(got, np.asarray(ref))
    if pid == 0:
        # evidence for the parent test's SINGLE-process restore of this
        # multi-process checkpoint (test_multiprocess.py)
        np.save(os.path.join(workdir, "ck_fsdp_expected.npy"),
                np.asarray(ref))
        with open(os.path.join(workdir, "ck_fsdp_leaf.json"), "w") as f:
            _json.dump({"param_leaf_index": big_idx}, f)
    print(f"[{pid}] fsdp sharded save/restore ok (no full-leaf gather)")

    # --- round 4: STREAMED sharded restore (O(local shards) host memory).
    # With `shardings` the restore must read only the regions this
    # process's devices need — the full-host assembly path must never
    # run. Enforced by stubbing it out, then every local shard is value-
    # checked against the live state.
    orig_assemble = ckpt._assemble_shards

    def _no_full_assembly(*a, **k):
        raise AssertionError(
            "restore(shardings=...) must stream shards, not assemble "
            "full leaves on host"
        )

    ckpt._assemble_shards = _no_full_assembly
    try:
        streamed = ckpt.restore(ck2, abstract, shardings=shardings)
    finally:
        ckpt._assemble_shards = orig_assemble
    for got_leaf, want_leaf in zip(
        jax.tree_util.tree_leaves(streamed.params), leaves
    ):
        for a, b in zip(
            got_leaf.addressable_shards, want_leaf.addressable_shards
        ):
            np.testing.assert_allclose(
                np.asarray(a.data), np.asarray(b.data)
            )
    print(f"[{pid}] fsdp STREAMED restore ok (no full-leaf host assembly)")

    # --- LM task multi-process: token shards, grad sync, perplexity ---
    cfg_lm = TrainConfig(
        model="lm_tiny",
        dataset="synthetic_text",
        batch_size=4,  # x4 global devices = 16 global
        seq_len=32,
        synthetic_size=32768,
        epochs=1,
        max_steps_per_epoch=3,
        optimizer="adamw",
        learning_rate=1e-3,
        log_every_steps=0,
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
        mesh=MeshConfig(data=-1),
    )
    lm_tr = Trainer(cfg_lm)
    lm_summary = lm_tr.fit()
    assert lm_summary["steps"] == 3, lm_summary
    assert np.isfinite(lm_summary["perplexity"]), lm_summary
    leaf = jax.tree_util.tree_leaves(lm_tr.state.params)[0]
    host_leaf = np.asarray(jax.device_get(leaf)).ravel()[:8]
    g = multihost_utils.process_allgather(host_leaf)
    np.testing.assert_allclose(g[0], g[1], rtol=0, atol=0)
    print(f"[{pid}] lm task multi-process ok")

    # --- assert_in_sync MUST fire on divergent fingerprints ---
    fired = False
    try:
        assert_in_sync(1000 + pid, what="deliberate mismatch")
    except RuntimeError as e:
        fired = True
        assert "out of sync" in str(e)
    assert fired, "assert_in_sync did not detect the mismatch"
    print(f"[{pid}] sync-mismatch detection ok")

    print("ALL_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
